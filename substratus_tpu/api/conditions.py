"""Status condition types + reasons (reference: api/v1/conditions.go:3-32)."""
from __future__ import annotations

import datetime
from dataclasses import dataclass
from typing import List, Optional

CONDITION_UPLOADED = "Uploaded"
CONDITION_BUILT = "Built"
CONDITION_COMPLETE = "Complete"
CONDITION_SERVING = "Serving"
CONDITION_DEPLOYED = "Deployed"

REASON_JOB_NOT_COMPLETE = "JobNotComplete"
REASON_JOB_COMPLETE = "JobComplete"
REASON_JOB_FAILED = "JobFailed"
REASON_POD_READY = "PodReady"
REASON_POD_NOT_READY = "PodNotReady"
REASON_BUILD_JOB_RUNNING = "ContainerBuilding"
REASON_BUILD_JOB_COMPLETE = "ContainerBuilt"
REASON_UPLOAD_FOUND = "UploadFound"
REASON_AWAITING_UPLOAD = "AwaitingUpload"
REASON_MODEL_NOT_FOUND = "ModelNotFound"
REASON_MODEL_NOT_READY = "ModelNotReady"
REASON_DATASET_NOT_FOUND = "DatasetNotFound"
REASON_DATASET_NOT_READY = "DatasetNotReady"
REASON_DEPLOYMENT_READY = "DeploymentReady"
REASON_DEPLOYMENT_NOT_READY = "DeploymentNotReady"
REASON_SUSPENDED = "Suspended"
REASON_INVALID_SPEC = "InvalidSpec"


@dataclass
class Condition:
    type: str = ""
    status: str = "False"  # "True" | "False" | "Unknown"
    reason: Optional[str] = None
    message: Optional[str] = None
    last_transition_time: Optional[str] = None
    observed_generation: Optional[int] = None


def now() -> str:
    return (
        datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )


def set_condition(conditions: List[Condition], new: Condition) -> List[Condition]:
    """Upsert by type; bump lastTransitionTime only on status change
    (metav1.SetStatusCondition semantics)."""
    for i, c in enumerate(conditions):
        if c.type == new.type:
            if c.status == new.status:
                new.last_transition_time = c.last_transition_time
            else:
                new.last_transition_time = new.last_transition_time or now()
            conditions[i] = new
            return conditions
    new.last_transition_time = new.last_transition_time or now()
    conditions.append(new)
    return conditions


def is_true(conditions: List[Condition], ctype: str) -> bool:
    return any(c.type == ctype and c.status == "True" for c in conditions)
