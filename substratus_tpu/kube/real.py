"""REST client against a real Kubernetes apiserver.

The reference uses client-go/controller-runtime (internal/client/client.go).
This implementation speaks the same REST surface with stdlib HTTP: CRUD on
the substratus.ai CRs and the core/batch/apps/jobset resources the
controllers create, watch streams feeding Manager listeners, and the pod
streaming subresources — logs (REST), exec and port-forward (WebSocket,
kube/ws.py) — that the reference reaches through client-go SPDY
(internal/client/sync.go:137-176, port_forward.go:21-44). In-cluster config
comes from the standard serviceaccount token mount; out-of-cluster from
kubeconfig via kube/config.py (tokens, client certs, exec plugins).
"""
from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import ssl
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, Iterator, List, Optional

from substratus_tpu.api.types import GROUP, VERSION
from substratus_tpu.observability.tracing import (
    current_trace_id as _current_trace_id,
)
from substratus_tpu.kube.client import (
    Conflict,
    KubeClient,
    KubeError,
    NotFound,
    Obj,
)

# kind -> (api prefix, plural)
RESOURCE_MAP: Dict[str, tuple] = {
    "Dataset": (f"/apis/{GROUP}/{VERSION}", "datasets"),
    "Model": (f"/apis/{GROUP}/{VERSION}", "models"),
    "Notebook": (f"/apis/{GROUP}/{VERSION}", "notebooks"),
    "Server": (f"/apis/{GROUP}/{VERSION}", "servers"),
    "Pod": ("/api/v1", "pods"),
    "Service": ("/api/v1", "services"),
    "ConfigMap": ("/api/v1", "configmaps"),
    "Secret": ("/api/v1", "secrets"),
    "ServiceAccount": ("/api/v1", "serviceaccounts"),
    "Job": ("/apis/batch/v1", "jobs"),
    "Deployment": ("/apis/apps/v1", "deployments"),
    "JobSet": ("/apis/jobset.x-k8s.io/v1alpha2", "jobsets"),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases"),
    # Controller event stream write-through (observability/events.py).
    "Event": ("/api/v1", "events"),
    # Cluster-scoped, create-only review APIs (metrics RBAC —
    # observability/authz.py; kube-rbac-proxy parity).
    "TokenReview": ("/apis/authentication.k8s.io/v1", "tokenreviews"),
    "SubjectAccessReview": (
        "/apis/authorization.k8s.io/v1", "subjectaccessreviews"
    ),
}

# Kinds with no namespace segment in their URL (and no watch support).
CLUSTER_SCOPED = ("TokenReview", "SubjectAccessReview")

# Kinds the controller watches. Lease is deliberately excluded: the elector
# only gets/updates one Lease, and a cluster-wide Lease watch would stream
# every node heartbeat and kube-system leader renewal into the workqueue
# (and typically 403 under the manager's RBAC anyway).
WATCHED_KINDS = tuple(
    k for k in RESOURCE_MAP if k != "Lease" and k not in CLUSTER_SCOPED
)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class RealKube(KubeClient):
    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        verify: bool = True,
        cert_file: Optional[str] = None,
        key_file: Optional[str] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self._listeners: List[Callable[[str, Obj], None]] = []
        if ca_file:
            self._ctx = ssl.create_default_context(cafile=ca_file)
        elif not verify:
            self._ctx = ssl._create_unverified_context()
        else:
            self._ctx = ssl.create_default_context()
        if cert_file:
            self._ctx.load_cert_chain(cert_file, key_file)
        self._watch_threads: List[threading.Thread] = []
        self._stop = threading.Event()

    @classmethod
    def in_cluster(cls) -> "RealKube":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        return cls(
            f"https://{host}:{port}", token=token, ca_file=f"{SA_DIR}/ca.crt"
        )

    # -- plumbing ----------------------------------------------------------

    def _path(self, kind: str, namespace: Optional[str], name: Optional[str] = None,
              subresource: Optional[str] = None) -> str:
        prefix, plural = RESOURCE_MAP[kind]
        p = prefix
        if namespace:
            p += f"/namespaces/{namespace}"
        p += f"/{plural}"
        if name:
            p += f"/{name}"
        if subresource:
            p += f"/{subresource}"
        return p

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 query: str = "") -> Any:
        url = self.base_url + path + (f"?{query}" if query else "")
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, context=self._ctx, timeout=30) as r:
                payload = r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise NotFound(path)
            if e.code == 409:
                raise Conflict(path)
            raise KubeError(f"{method} {path}: {e.code} {e.read()[:500]!r}")
        return json.loads(payload) if payload else None

    # -- KubeClient --------------------------------------------------------

    def get(self, kind: str, namespace: str, name: str) -> Obj:
        return self._request("GET", self._path(kind, namespace, name))

    def list(self, kind: str, namespace: Optional[str] = None) -> List[Obj]:
        out = self._request("GET", self._path(kind, namespace))
        items = out.get("items", [])
        for it in items:  # list items omit kind/apiVersion
            it.setdefault("kind", kind)
        return items

    def create(self, obj: Obj) -> Obj:
        if obj["kind"] in CLUSTER_SCOPED:
            return self._request(
                "POST", self._path(obj["kind"], None), obj
            )
        md = obj["metadata"]
        return self._request(
            "POST", self._path(obj["kind"], md.get("namespace", "default")), obj
        )

    def update(self, obj: Obj) -> Obj:
        md = obj["metadata"]
        return self._request(
            "PUT",
            self._path(obj["kind"], md.get("namespace", "default"), md["name"]),
            obj,
        )

    def update_status(self, obj: Obj) -> Obj:
        md = obj["metadata"]
        return self._request(
            "PUT",
            self._path(
                obj["kind"], md.get("namespace", "default"), md["name"], "status"
            ),
            obj,
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", self._path(kind, namespace, name))

    def add_listener(self, fn: Callable[[str, Obj], None]) -> None:
        start_watches = not self._listeners
        self._listeners.append(fn)
        if start_watches:
            for kind in WATCHED_KINDS:
                t = threading.Thread(
                    target=self._watch_loop, args=(kind,), daemon=True
                )
                t.start()
                self._watch_threads.append(t)

    # -- watch -------------------------------------------------------------

    def _watch_loop(self, kind: str) -> None:
        rv = ""
        while not self._stop.is_set():
            try:
                query = "watch=true" + (f"&resourceVersion={rv}" if rv else "")
                url = self.base_url + self._path(kind, None) + "?" + query
                req = urllib.request.Request(url)
                req.add_header("Accept", "application/json")
                if self.token:
                    req.add_header("Authorization", f"Bearer {self.token}")
                with urllib.request.urlopen(
                    req, context=self._ctx, timeout=330
                ) as r:
                    for line in r:
                        if self._stop.is_set():
                            return
                        event = json.loads(line)
                        obj = event.get("object", {})
                        obj.setdefault("kind", kind)
                        rv = obj.get("metadata", {}).get("resourceVersion", rv)
                        for fn in self._listeners:
                            try:
                                fn(event.get("type", "MODIFIED"), obj)
                            except Exception:  # sublint: allow[broad-except]: a buggy listener must not kill the shared watch; logged with trace id
                                logging.getLogger(__name__).exception(
                                    "watch listener failed for %s "
                                    "(trace_id=%s)", kind,
                                    _current_trace_id(),
                                )
            except (OSError, http.client.HTTPException, ValueError) as e:
                # Watch dropped (timeout, apiserver restart, truncated
                # JSON): resume from the last resourceVersion. OSError
                # covers socket/ssl/urllib.error; ValueError covers
                # json decode. Anything else is a real bug and raises.
                logging.getLogger(__name__).debug(
                    "watch %s dropped (%s: %s); resuming", kind,
                    type(e).__name__, e,
                )
                self._stop.wait(2.0)

    def stop(self) -> None:
        self._stop.set()

    # -- pod streaming subresources (logs / exec / port-forward) -----------

    def list_selected(self, kind: str, namespace: str,
                      label_selector: str) -> List[Obj]:
        out = self._request(
            "GET", self._path(kind, namespace),
            query="labelSelector=" + urllib.parse.quote(label_selector),
        )
        items = out.get("items", [])
        for it in items:
            it.setdefault("kind", kind)
        return items

    def pod_logs(
        self,
        namespace: str,
        pod: str,
        *,
        container: Optional[str] = None,
        tail: Optional[int] = None,
        follow: bool = False,
    ) -> Iterator[str]:
        """Stream a pod's log lines (GET .../pods/{pod}/log)."""
        params = {}
        if container:
            params["container"] = container
        if tail is not None:
            params["tailLines"] = str(tail)
        if follow:
            params["follow"] = "true"
        url = (
            self.base_url + self._path("Pod", namespace, pod, "log")
            + ("?" + urllib.parse.urlencode(params) if params else "")
        )
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(
                req, context=self._ctx, timeout=None if follow else 30
            ) as r:
                for line in r:
                    yield line.decode(errors="replace").rstrip("\n")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise NotFound(pod)
            raise KubeError(f"logs {pod}: {e.code} {e.read()[:300]!r}")

    def _ws_connect(self, path: str, query: str, subprotocols):
        from substratus_tpu.kube.ws import WebSocket

        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return WebSocket.connect(
            self.base_url + path + "?" + query,
            headers=headers,
            subprotocols=subprotocols,
            ssl_context=self._ctx if self.base_url.startswith("https") else None,
        )

    def pod_exec_stream(
        self,
        namespace: str,
        pod: str,
        command: List[str],
        *,
        stdin: bool = False,
        container: Optional[str] = None,
    ):
        """Open exec against the pod; returns a kube.ws.ExecStream."""
        from substratus_tpu.kube.ws import ExecStream

        params = [("stdout", "1"), ("stderr", "1")]
        if stdin:
            params.append(("stdin", "1"))
        if container:
            params.append(("container", container))
        params += [("command", c) for c in command]
        ws = self._ws_connect(
            self._path("Pod", namespace, pod, "exec"),
            urllib.parse.urlencode(params),
            ("v4.channel.k8s.io",),
        )
        return ExecStream(ws)

    def pod_exec(
        self,
        namespace: str,
        pod: str,
        command: List[str],
        *,
        stdin_data: Optional[bytes] = None,
        container: Optional[str] = None,
    ):
        """Run a command to completion -> (rc, stdout, stderr)."""
        stream = self.pod_exec_stream(
            namespace, pod, command,
            stdin=stdin_data is not None, container=container,
        )
        if stdin_data is not None:
            for off in range(0, len(stdin_data), 65536):
                stream.send_stdin(stdin_data[off:off + 65536])
        out, err, status = stream.run()
        rc = 0
        if status.get("status") == "Failure":
            rc = 1
            for cause in (status.get("details") or {}).get("causes") or []:
                if cause.get("reason") == "ExitCode":
                    rc = int(cause.get("message", 1))
        return rc, out, err

    def cp_from_pod(self, namespace: str, pod: str, remote_path: str,
                    local_path: str) -> bool:
        """Download one file (exec `cat`; the reference's sync.go uses the
        same per-file strategy through its cp helper)."""
        rc, out, err = self.pod_exec(
            namespace, pod, ["cat", remote_path]
        )
        if rc != 0:
            return False
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(out)
        return True

    def cp_to_pod(self, namespace: str, pod: str, local_path: str,
                  remote_path: str) -> bool:
        """Upload one file. `head -c N > path` consumes exactly the payload
        size, so completion needs no stdin-EOF signal (the v4 channel
        protocol has none)."""
        import shlex

        with open(local_path, "rb") as f:
            data = f.read()
        rc, _, err = self.pod_exec(
            namespace, pod,
            ["sh", "-c",
             f"head -c {len(data)} > {shlex.quote(remote_path)}"],
            stdin_data=data,
        )
        return rc == 0

    def port_forward(
        self,
        namespace: str,
        pod: str,
        local_port: int,
        remote_port: int,
        *,
        stop: Optional[threading.Event] = None,
        ready: Optional[threading.Event] = None,
    ) -> None:
        """Forward localhost:local_port -> pod:remote_port until `stop`.

        Accept loop on a local listener; each TCP connection gets its own
        WebSocket stream pair (the portforward.k8s.io protocol is
        per-connection), pumped by a pair of threads.
        """
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        listener.bind(("127.0.0.1", local_port))
        listener.listen(8)
        listener.settimeout(0.5)
        if ready is not None:
            ready.set()
        # Consecutive WS dial failures poison the forward: raising from
        # here (instead of silently eating them in connection threads)
        # reaches cli/sync.py's retry/backoff exactly like a dead kubectl
        # subprocess did. The counter is per-forward state (a dict shared
        # only with this forward's connection threads), not an instance
        # attribute: two concurrent port_forward calls on one client must
        # not poison each other's failure counts.
        pf_state: dict = {"failures": 0, "last_error": None}
        try:
            while not (stop is not None and stop.is_set()):
                if pf_state["failures"] >= 3:
                    raise KubeError(
                        f"port-forward to {namespace}/{pod}:{remote_port} "
                        f"failing: {pf_state['last_error']}"
                    )
                try:
                    conn, _ = listener.accept()
                except socket.timeout:
                    continue
                threading.Thread(
                    target=self._forward_one,
                    args=(namespace, pod, remote_port, conn, stop, pf_state),
                    daemon=True,
                ).start()
        finally:
            listener.close()

    def _forward_one(
        self, namespace, pod, remote_port, conn, stop, pf_state
    ) -> None:
        from substratus_tpu.kube.ws import PortForwardStream

        log = logging.getLogger(__name__)
        try:
            ws = self._ws_connect(
                self._path("Pod", namespace, pod, "portforward"),
                urllib.parse.urlencode([("ports", str(remote_port))]),
                ("portforward.k8s.io",),
            )
        except Exception as e:  # sublint: allow[broad-except]: dial failure of any kind is surfaced via pf_state to the accept loop and logged
            pf_state["failures"] += 1
            pf_state["last_error"] = e
            log.warning("port-forward dial %s/%s:%s failed: %s",
                        namespace, pod, remote_port, e)
            conn.close()
            return
        pf_state["failures"] = 0
        stream = PortForwardStream(ws)

        def pump_out():
            try:
                for chunk in stream.chunks():
                    conn.sendall(chunk)
            except OSError:
                pass  # local browser/tool hung up; routine
            except Exception as e:  # sublint: allow[broad-except]: WSError from the error channel — pod-side failure worth logging, never fatal
                # (kubectl printed these too)
                log.warning("port-forward stream %s/%s:%s: %s",
                            namespace, pod, remote_port, e)
            finally:
                try:
                    conn.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=pump_out, daemon=True)
        t.start()
        try:
            while not (stop is not None and stop.is_set()):
                data = conn.recv(65536)
                if not data:
                    break
                stream.send(data)
        except OSError:
            pass
        finally:
            stream.close()
            conn.close()
