"""REST client against a real Kubernetes apiserver.

The reference uses client-go/controller-runtime (internal/client/client.go).
This implementation speaks the same REST surface with stdlib HTTP: CRUD on
the substratus.ai CRs and the core/batch/apps/jobset resources the
controllers create, plus watch streams feeding Manager listeners. In-cluster
config comes from the standard serviceaccount token mount; out-of-cluster
from $KUBECONFIG (token/insecure-skip-tls only — exec plugins are out of
scope for round 1).
"""
from __future__ import annotations

import json
import os
import ssl
import threading
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional

from substratus_tpu.api.types import GROUP, VERSION
from substratus_tpu.kube.client import (
    Conflict,
    KubeClient,
    KubeError,
    NotFound,
    Obj,
)

# kind -> (api prefix, plural)
RESOURCE_MAP: Dict[str, tuple] = {
    "Dataset": (f"/apis/{GROUP}/{VERSION}", "datasets"),
    "Model": (f"/apis/{GROUP}/{VERSION}", "models"),
    "Notebook": (f"/apis/{GROUP}/{VERSION}", "notebooks"),
    "Server": (f"/apis/{GROUP}/{VERSION}", "servers"),
    "Pod": ("/api/v1", "pods"),
    "Service": ("/api/v1", "services"),
    "ConfigMap": ("/api/v1", "configmaps"),
    "Secret": ("/api/v1", "secrets"),
    "ServiceAccount": ("/api/v1", "serviceaccounts"),
    "Job": ("/apis/batch/v1", "jobs"),
    "Deployment": ("/apis/apps/v1", "deployments"),
    "JobSet": ("/apis/jobset.x-k8s.io/v1alpha2", "jobsets"),
    "Lease": ("/apis/coordination.k8s.io/v1", "leases"),
}

# Kinds the controller watches. Lease is deliberately excluded: the elector
# only gets/updates one Lease, and a cluster-wide Lease watch would stream
# every node heartbeat and kube-system leader renewal into the workqueue
# (and typically 403 under the manager's RBAC anyway).
WATCHED_KINDS = tuple(k for k in RESOURCE_MAP if k != "Lease")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class RealKube(KubeClient):
    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        ca_file: Optional[str] = None,
        verify: bool = True,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self._listeners: List[Callable[[str, Obj], None]] = []
        if ca_file:
            self._ctx = ssl.create_default_context(cafile=ca_file)
        elif not verify:
            self._ctx = ssl._create_unverified_context()
        else:
            self._ctx = ssl.create_default_context()
        self._watch_threads: List[threading.Thread] = []
        self._stop = threading.Event()

    @classmethod
    def in_cluster(cls) -> "RealKube":
        host = os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default.svc")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        return cls(
            f"https://{host}:{port}", token=token, ca_file=f"{SA_DIR}/ca.crt"
        )

    # -- plumbing ----------------------------------------------------------

    def _path(self, kind: str, namespace: Optional[str], name: Optional[str] = None,
              subresource: Optional[str] = None) -> str:
        prefix, plural = RESOURCE_MAP[kind]
        p = prefix
        if namespace:
            p += f"/namespaces/{namespace}"
        p += f"/{plural}"
        if name:
            p += f"/{name}"
        if subresource:
            p += f"/{subresource}"
        return p

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 query: str = "") -> Any:
        url = self.base_url + path + (f"?{query}" if query else "")
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if data is not None:
            req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, context=self._ctx, timeout=30) as r:
                payload = r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise NotFound(path)
            if e.code == 409:
                raise Conflict(path)
            raise KubeError(f"{method} {path}: {e.code} {e.read()[:500]!r}")
        return json.loads(payload) if payload else None

    # -- KubeClient --------------------------------------------------------

    def get(self, kind: str, namespace: str, name: str) -> Obj:
        return self._request("GET", self._path(kind, namespace, name))

    def list(self, kind: str, namespace: Optional[str] = None) -> List[Obj]:
        out = self._request("GET", self._path(kind, namespace))
        items = out.get("items", [])
        for it in items:  # list items omit kind/apiVersion
            it.setdefault("kind", kind)
        return items

    def create(self, obj: Obj) -> Obj:
        md = obj["metadata"]
        return self._request(
            "POST", self._path(obj["kind"], md.get("namespace", "default")), obj
        )

    def update(self, obj: Obj) -> Obj:
        md = obj["metadata"]
        return self._request(
            "PUT",
            self._path(obj["kind"], md.get("namespace", "default"), md["name"]),
            obj,
        )

    def update_status(self, obj: Obj) -> Obj:
        md = obj["metadata"]
        return self._request(
            "PUT",
            self._path(
                obj["kind"], md.get("namespace", "default"), md["name"], "status"
            ),
            obj,
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._request("DELETE", self._path(kind, namespace, name))

    def add_listener(self, fn: Callable[[str, Obj], None]) -> None:
        start_watches = not self._listeners
        self._listeners.append(fn)
        if start_watches:
            for kind in WATCHED_KINDS:
                t = threading.Thread(
                    target=self._watch_loop, args=(kind,), daemon=True
                )
                t.start()
                self._watch_threads.append(t)

    # -- watch -------------------------------------------------------------

    def _watch_loop(self, kind: str) -> None:
        rv = ""
        while not self._stop.is_set():
            try:
                query = "watch=true" + (f"&resourceVersion={rv}" if rv else "")
                url = self.base_url + self._path(kind, None) + "?" + query
                req = urllib.request.Request(url)
                req.add_header("Accept", "application/json")
                if self.token:
                    req.add_header("Authorization", f"Bearer {self.token}")
                with urllib.request.urlopen(
                    req, context=self._ctx, timeout=330
                ) as r:
                    for line in r:
                        if self._stop.is_set():
                            return
                        event = json.loads(line)
                        obj = event.get("object", {})
                        obj.setdefault("kind", kind)
                        rv = obj.get("metadata", {}).get("resourceVersion", rv)
                        for fn in self._listeners:
                            fn(event.get("type", "MODIFIED"), obj)
            except Exception:
                # watch dropped (timeout, apiserver restart): resume.
                self._stop.wait(2.0)

    def stop(self) -> None:
        self._stop.set()
