"""In-memory fake apiserver — the envtest tier of the test strategy.

The reference's integration backbone is envtest: a real apiserver+etcd with
faked Job/Pod status because no kubelet runs (SURVEY.md §4 tier 2,
internal/controller/main_test.go:245-265). This fake goes one step lighter
(pure in-memory store + synchronous listener fanout) which buys the tests
something envtest can't: deterministic, poll-free assertions — after
`manager.run_until_idle()` every reconcile consequence is visible.

Data-plane faking helpers mirror the reference's: `mark_job_complete`,
`mark_pod_ready`, `mark_deployment_ready`, `mark_jobset_complete`.
"""
from __future__ import annotations

import copy
import datetime
import threading
from typing import Any, Callable, Dict, List, Optional

from substratus_tpu.kube.client import (
    Conflict, Invalid, KubeClient, NotFound, Obj, fold_secret_string_data,
)


class FakeKube(KubeClient):
    def __init__(self, validate: bool = True):
        # Schema validation of every stored write (kube/schema.py): a
        # manifest a real apiserver would 400/422 must fail the suite too.
        self.validate = validate
        self._store: Dict[tuple, Obj] = {}
        self._rv = 0
        self._uid = 0
        self._listeners: List[Callable[[str, Obj], None]] = []
        self._lock = threading.RLock()
        # Auth tables for the create-only review APIs (metrics RBAC tests):
        # token -> {"username": ..., "groups": [...]}; users allowed to GET
        # non-resource URLs like /metrics.
        self.tokens: Dict[str, Dict[str, Any]] = {}
        self.metrics_readers: set = set()

    def _review(self, obj: Obj) -> Obj:
        """Evaluate TokenReview / SubjectAccessReview like the apiserver
        (authentication/authorization.k8s.io are create-only, unstored)."""
        obj = copy.deepcopy(obj)
        spec = obj.get("spec", {})
        if obj["kind"] == "TokenReview":
            user = self.tokens.get(spec.get("token", ""))
            obj["status"] = (
                {"authenticated": True, "user": dict(user)}
                if user else {"authenticated": False}
            )
        else:
            allowed = spec.get("user") in self.metrics_readers
            obj["status"] = {"allowed": allowed}
        return obj

    # -- helpers -----------------------------------------------------------

    def _key(self, kind: str, namespace: str, name: str) -> tuple:
        return (kind, namespace or "default", name)

    def _bump(self, obj: Obj) -> None:
        self._rv += 1
        obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)

    def _notify(self, event: str, obj: Obj) -> None:
        for fn in list(self._listeners):
            fn(event, copy.deepcopy(obj))

    def _validate(self, obj: Obj) -> None:
        if not self.validate:
            return
        from substratus_tpu.kube import schema

        schema.validate(obj)

    # Secret stringData -> data fold shared with the reconcilers'
    # desired-state normalization (they must agree; see client.py).
    _fold_secret = staticmethod(fold_secret_string_data)

    # Real-apiserver immutability semantics (conformance: each rule names
    # the behavior it mirrors — see tests/test_fakekube_conformance.py).
    _POD_MUTABLE = ("activeDeadlineSeconds", "terminationGracePeriodSeconds",
                    "tolerations")

    def _enforce_immutable(self, current: Obj, new: Obj) -> None:
        kind = new["kind"]
        old_spec = current.get("spec") or {}
        new_spec = new.get("spec") or {}
        if kind == "Service":
            # clusterIP is immutable once allocated (apiserver: "spec:
            # Invalid value ... field is immutable").
            old_ip = old_spec.get("clusterIP")
            if old_ip and new_spec.get("clusterIP") != old_ip:
                raise Invalid(
                    f"Service {new['metadata']['name']}: spec.clusterIP: "
                    "field is immutable"
                )
        elif kind == "Job":
            # batch/v1 Job: template/selector/completionMode immutable
            # (parallelism/suspend/activeDeadlineSeconds are the mutable
            # exceptions).
            for field in ("template", "selector", "completionMode"):
                if old_spec.get(field) != new_spec.get(field):
                    raise Invalid(
                        f"Job {new['metadata']['name']}: spec.{field}: "
                        "field is immutable"
                    )
        elif kind == "Pod":
            # Pod spec is immutable apart from container images,
            # tolerations (additions), and the two deadline fields.
            def reduced(spec: Obj) -> Obj:
                s = copy.deepcopy(spec)
                for f in self._POD_MUTABLE:
                    s.pop(f, None)
                for c in s.get("containers", []) + s.get(
                    "initContainers", []
                ):
                    c.pop("image", None)
                return s

            if reduced(old_spec) != reduced(new_spec):
                raise Invalid(
                    f"Pod {new['metadata']['name']}: pod updates may not "
                    "change fields other than image, tolerations, or "
                    "deadlines"
                )
            # The apiserver only allows ADDING tolerations: every existing
            # toleration must still match some entry in the new list,
            # compared with tolerationSeconds excluded (apiserver
            # validateOnlyAddedTolerations) — reordering and
            # tolerationSeconds changes are allowed, removal/modification
            # is not.
            def _tol_key(t: Obj):
                return tuple(
                    sorted(
                        (k, v) for k, v in t.items()
                        if k != "tolerationSeconds"
                    )
                )

            new_keys = {
                _tol_key(t) for t in new_spec.get("tolerations") or []
            }
            for t in old_spec.get("tolerations") or []:
                if _tol_key(t) not in new_keys:
                    raise Invalid(
                        f"Pod {new['metadata']['name']}: spec.tolerations: "
                        "existing tolerations may not be modified or "
                        "removed, only new tolerations may be added"
                    )
        elif kind in ("ConfigMap", "Secret"):
            if current.get("immutable") and (
                new.get("data") != current.get("data")
                or new.get("binaryData") != current.get("binaryData")
            ):
                raise Invalid(
                    f"{kind} {new['metadata']['name']}: field is immutable "
                    "when `immutable` is set"
                )

    # -- KubeClient --------------------------------------------------------

    def get(self, kind: str, namespace: str, name: str) -> Obj:
        with self._lock:
            key = self._key(kind, namespace, name)
            if key not in self._store:
                raise NotFound(f"{kind} {namespace}/{name} not found")
            return copy.deepcopy(self._store[key])

    def list(self, kind: str, namespace: Optional[str] = None) -> List[Obj]:
        with self._lock:
            return [
                copy.deepcopy(o)
                for (k, ns, _), o in sorted(self._store.items())
                if k == kind and (namespace is None or ns == namespace)
            ]

    def create(self, obj: Obj) -> Obj:
        if obj.get("kind") in ("TokenReview", "SubjectAccessReview"):
            with self._lock:
                return self._review(obj)
        with self._lock:
            obj = copy.deepcopy(obj)
            md = obj.setdefault("metadata", {})
            md.setdefault("namespace", "default")
            key = self._key(obj["kind"], md["namespace"], md["name"])
            if key in self._store:
                raise Conflict(f"{key} already exists")
            self._uid += 1
            md.setdefault("uid", f"uid-{self._uid}")
            md.setdefault("generation", 1)
            md.setdefault(
                "creationTimestamp",
                datetime.datetime.now(datetime.timezone.utc).strftime(
                    "%Y-%m-%dT%H:%M:%SZ"
                ),
            )
            self._validate(obj)
            self._fold_secret(obj)
            self._bump(obj)
            self._store[key] = obj
            out = copy.deepcopy(obj)
        self._notify("ADDED", out)
        return out

    def _update(self, obj: Obj, status_only: bool) -> Obj:
        with self._lock:
            obj = copy.deepcopy(obj)
            md = obj.setdefault("metadata", {})
            key = self._key(obj["kind"], md.get("namespace", "default"), md["name"])
            if key not in self._store:
                raise NotFound(f"{key} not found")
            current = self._store[key]
            sent_rv = md.get("resourceVersion")
            cur_rv = current["metadata"].get("resourceVersion")
            if sent_rv is not None and sent_rv != cur_rv:
                raise Conflict(f"{key}: resourceVersion {sent_rv} != {cur_rv}")
            new = copy.deepcopy(current)
            if status_only:
                new["status"] = copy.deepcopy(obj.get("status", {}))
                self._validate(new)
            else:
                if obj.get("spec") != current.get("spec"):
                    new["metadata"]["generation"] = (
                        current["metadata"].get("generation", 1) + 1
                    )
                # A real apiserver PUT replaces EVERY non-status section
                # (spec, data, immutable, type, ...) — an absent (or null)
                # section means it's gone, never a literal `spec: null` on
                # spec-less kinds.
                managed = ("apiVersion", "kind", "metadata", "status")
                for k in list(new):
                    if k not in managed and obj.get(k) is None:
                        new.pop(k)
                for k, v in obj.items():
                    if k not in managed and v is not None:
                        new[k] = copy.deepcopy(v)
                for k in ("labels", "annotations", "ownerReferences"):
                    if k in md:
                        new["metadata"][k] = copy.deepcopy(md[k])
                self._validate(new)
                self._fold_secret(new)
                self._enforce_immutable(current, new)
            self._bump(new)
            self._store[key] = new
            out = copy.deepcopy(new)
        self._notify("MODIFIED", out)
        return out

    def update(self, obj: Obj) -> Obj:
        return self._update(obj, status_only=False)

    def update_status(self, obj: Obj) -> Obj:
        return self._update(obj, status_only=True)

    def delete(self, kind: str, namespace: str, name: str) -> None:
        with self._lock:
            key = self._key(kind, namespace, name)
            if key not in self._store:
                raise NotFound(f"{key} not found")
            obj = self._store.pop(key)
            # Owner-reference cascade (the garbage collection a real
            # apiserver performs), transitive: children of deleted objects
            # are deleted too, worklist over freshly removed uids.
            orphans = []
            pending = [obj.get("metadata", {}).get("uid")]
            while pending:
                uid = pending.pop()
                if not uid:
                    continue
                for ckey, child in list(self._store.items()):
                    refs = child.get("metadata", {}).get(
                        "ownerReferences", []
                    )
                    if any(r.get("uid") == uid for r in refs):
                        gone = self._store.pop(ckey)
                        orphans.append(gone)
                        pending.append(gone.get("metadata", {}).get("uid"))
        self._notify("DELETED", obj)
        for child in orphans:
            self._notify("DELETED", child)

    def add_listener(self, fn: Callable[[str, Obj], None]) -> None:
        self._listeners.append(fn)

    # -- data-plane fakes (reference main_test.go:245-265) -----------------

    def mark_job_complete(self, namespace: str, name: str, failed: bool = False):
        job = self.get("Job", namespace, name)
        if failed:
            job["status"] = {
                "conditions": [{"type": "Failed", "status": "True"}],
                "failed": 1,
            }
        else:
            job["status"] = {
                "conditions": [{"type": "Complete", "status": "True"}],
                "succeeded": 1,
            }
        self.update_status(job)

    def mark_jobset_complete(self, namespace: str, name: str, failed: bool = False):
        js = self.get("JobSet", namespace, name)
        ctype = "Failed" if failed else "Completed"
        js["status"] = {"conditions": [{"type": ctype, "status": "True"}]}
        self.update_status(js)

    def mark_pod_ready(self, namespace: str, name: str):
        pod = self.get("Pod", namespace, name)
        pod["status"] = {
            "phase": "Running",
            "conditions": [{"type": "Ready", "status": "True"}],
        }
        self.update_status(pod)

    def mark_deployment_ready(self, namespace: str, name: str):
        dep = self.get("Deployment", namespace, name)
        replicas = dep.get("spec", {}).get("replicas", 1)
        dep["status"] = {"readyReplicas": replicas, "replicas": replicas}
        self.update_status(dep)
