"""Kubernetes client interface used by controllers, CLI and tests.

The reference uses controller-runtime's cached client + dynamic REST mapper
(internal/client/client.go:68-112). Here the surface is a small abstract
API over plain-dict objects (apiVersion/kind/metadata/spec/status), with two
implementations: kube.fake.FakeKube (in-memory apiserver for tests and local
dev — the envtest equivalent) and kube.real.RealKube (REST against an actual
apiserver)."""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, List, Optional

Obj = Dict[str, Any]


class KubeError(Exception):
    pass


class NotFound(KubeError):
    pass


class Conflict(KubeError):
    pass


class Invalid(KubeError):
    """The apiserver rejected the write (422): schema violation or an
    attempt to mutate an immutable field."""


def fold_secret_string_data(obj: Obj) -> None:
    """apiserver semantics for Secrets, in one place: stringData is
    write-only — it folds into data (base64, stringData winning on key
    conflict) and is NEVER stored or returned. Used by the fake apiserver
    when storing and by reconcilers when normalizing desired state; the
    two MUST agree or drift detection hot-loops."""
    import base64

    if obj.get("kind") != "Secret" or "stringData" not in obj:
        return
    data = obj.setdefault("data", {})
    for k, v in (obj.pop("stringData") or {}).items():
        data[k] = base64.b64encode(str(v).encode()).decode()


def obj_key(obj: Obj) -> tuple:
    md = obj.get("metadata", {})
    return (obj.get("kind"), md.get("namespace", "default"), md.get("name"))


class KubeClient(ABC):
    @abstractmethod
    def get(self, kind: str, namespace: str, name: str) -> Obj: ...

    @abstractmethod
    def list(self, kind: str, namespace: Optional[str] = None) -> List[Obj]: ...

    @abstractmethod
    def create(self, obj: Obj) -> Obj: ...

    @abstractmethod
    def update(self, obj: Obj) -> Obj:
        """Replace spec/metadata (optimistic concurrency via resourceVersion)."""

    @abstractmethod
    def update_status(self, obj: Obj) -> Obj: ...

    @abstractmethod
    def delete(self, kind: str, namespace: str, name: str) -> None: ...

    @abstractmethod
    def add_listener(self, fn: Callable[[str, Obj], None]) -> None:
        """fn(event_type, obj) for every add/update/delete."""

    # -- convenience -------------------------------------------------------

    def get_or_none(self, kind: str, namespace: str, name: str) -> Optional[Obj]:
        try:
            return self.get(kind, namespace, name)
        except NotFound:
            return None

    def apply(self, obj: Obj, _retries: int = 5) -> Obj:
        """Server-side-apply-ish upsert: create, or merge spec/metadata
        onto the existing object (reference client/upload.go:110-124 uses
        SSA with field ownership).

        Conflict-safe: the merged update carries the read's
        resourceVersion, so a concurrent writer between our get and update
        surfaces as a Conflict (optimistic concurrency) and the
        get-merge-update is retried against the fresh object instead of
        silently clobbering the other writer (lost update)."""
        last: Optional[Exception] = None
        for _ in range(_retries):
            kind, ns, name = obj_key(obj)
            existing = self.get_or_none(kind, ns, name)
            if existing is None:
                try:
                    return self.create(obj)
                except Conflict as e:  # lost a create race; merge instead
                    last = e
                    continue
            merged = dict(existing)
            for section in ("spec", "data", "stringData"):
                if section in obj:
                    merged[section] = obj[section]
            md = dict(existing.get("metadata", {}))
            for k in ("labels", "annotations"):
                if obj.get("metadata", {}).get(k):
                    md.setdefault(k, {}).update(obj["metadata"][k])
            merged["metadata"] = md
            try:
                return self.update(merged)
            except Conflict as e:
                last = e
        raise last if last is not None else KubeError("apply: no attempts")
