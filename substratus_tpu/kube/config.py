"""kubeconfig loading with the full auth surface client-go gives the
reference for free (internal/cli/notebook.go:37-50): bearer tokens,
client certificates (inline -data or file paths), exec credential
plugins (client.authentication.k8s.io ExecCredential — what GKE's
gke-gcloud-auth-plugin speaks), CA bundles, and insecure-skip-tls.

Resolution order per kubeconfig `user`:
  1. token / tokenFile
  2. client-certificate(-data) + client-key(-data)
  3. exec plugin -> ExecCredential {token | clientCertificateData+KeyData}
"""
from __future__ import annotations

import atexit
import base64
import json
import os
import subprocess
import tempfile
from typing import Optional

import yaml

from substratus_tpu.kube.real import RealKube

SA_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"


def _write_tmp(content: str, suffix: str) -> str:
    """Secret material (client keys, exec-plugin certs) decoded to disk for
    ssl.load_cert_chain, which only takes paths. Mode 0600 via mkstemp and
    unlinked at interpreter exit — keys must not outlive the CLI run."""
    fd, path = tempfile.mkstemp(suffix=suffix)
    with os.fdopen(fd, "w") as f:
        f.write(content)
    atexit.register(_unlink_quiet, path)
    return path


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _materialize(data_b64: Optional[str], path: Optional[str],
                 suffix: str) -> Optional[str]:
    """Inline base64 -data wins over the file path; returns a file path."""
    if data_b64:
        return _write_tmp(base64.b64decode(data_b64).decode(), suffix)
    return path


def _run_exec_plugin(spec: dict) -> dict:
    """Run a client-go exec credential plugin; returns ExecCredential
    .status ({token} or {clientCertificateData, clientKeyData})."""
    env = dict(os.environ)
    for pair in spec.get("env") or []:
        env[pair["name"]] = pair["value"]
    api_version = spec.get("apiVersion",
                           "client.authentication.k8s.io/v1beta1")
    env["KUBERNETES_EXEC_INFO"] = json.dumps({
        "apiVersion": api_version,
        "kind": "ExecCredential",
        "spec": {"interactive": False},
    })
    cmd = [spec["command"], *(spec.get("args") or [])]
    proc = subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=60,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"credential plugin {spec['command']!r} failed: "
            f"{proc.stderr.strip()[:300]}"
        )
    cred = json.loads(proc.stdout)
    return cred.get("status") or {}


def client_from_kubeconfig(
    path: Optional[str] = None, context: Optional[str] = None
) -> RealKube:
    """Build a RealKube from a kubeconfig file (default: $KUBECONFIG or
    ~/.kube/config), honoring the named (or current-) context."""
    path = path or os.environ.get(
        "KUBECONFIG", os.path.expanduser("~/.kube/config")
    )
    with open(path) as f:
        kc = yaml.safe_load(f)

    ctx_name = context or kc.get("current-context")
    ctx = next(c for c in kc["contexts"] if c["name"] == ctx_name)["context"]
    cluster = next(
        c for c in kc["clusters"] if c["name"] == ctx["cluster"]
    )["cluster"]
    user = next(u for u in kc["users"] if u["name"] == ctx["user"])["user"]

    ca_file = _materialize(
        cluster.get("certificate-authority-data"),
        cluster.get("certificate-authority"),
        ".crt",
    )

    token = user.get("token")
    if not token and user.get("tokenFile"):
        with open(user["tokenFile"]) as f:
            token = f.read().strip()
    cert_file = _materialize(
        user.get("client-certificate-data"),
        user.get("client-certificate"), ".crt",
    )
    key_file = _materialize(
        user.get("client-key-data"), user.get("client-key"), ".key",
    )

    if not token and not cert_file and user.get("exec"):
        status = _run_exec_plugin(user["exec"])
        token = status.get("token")
        # ExecCredential cert/key fields hold PEM text directly.
        if status.get("clientCertificateData"):
            cert_file = _write_tmp(status["clientCertificateData"], ".crt")
            key_file = _write_tmp(status["clientKeyData"], ".key")

    return RealKube(
        cluster["server"],
        token=token,
        ca_file=ca_file,
        verify=not cluster.get("insecure-skip-tls-verify", False),
        cert_file=cert_file,
        key_file=key_file,
    )


def default_client() -> RealKube:
    """In-cluster service account when mounted, else kubeconfig."""
    if os.path.exists(SA_TOKEN):
        return RealKube.in_cluster()
    return client_from_kubeconfig()
