"""Structural schemas for every manifest the controllers emit or accept.

VERDICT r3 weak #4: the controllers were tested only against semantics the
fake apiserver's author wrote — a typo'd JobSet field (`failurePolicy.
maxRestart`) would pass every test and fail on a real cluster. This module
closes that hole: FakeKube validates every create/update against schemas
hand-derived from the upstream API references — core/v1, apps/v1, batch/v1,
coordination.k8s.io/v1 (kubernetes.io API reference) and
jobset.x-k8s.io/v1alpha2 (jobset.sigs.k8s.io API reference; the reference
project's JobSet usage is generated the same way, see
/root/reference/config/crd/bases for its generated-CRD rigor). The
substratus.ai CR schemas are NOT hand-written — they come from the same
api/crdgen.py output that `make manifests` ships, so the validator enforces
exactly what a real apiserver with our CRDs installed would.

Strictness note: a real apiserver *prunes* unknown fields on structural-CRD
objects and accepts built-ins with a warning; here an unknown field raises.
In a test, an unknown field is a typo, and failing loudly is the point.
None values are treated as absent (JSON serialization drops them).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from substratus_tpu.kube.client import KubeError


class SchemaError(KubeError):
    """The manifest does not match the API schema (real apiserver: 400/422)."""


# -- schema DSL (an openAPIV3Schema subset, same dialect crdgen emits) ------

STR = {"type": "string"}
INT = {"type": "integer"}
NUM = {"type": "number"}
BOOL = {"type": "boolean"}
INT_OR_STR = {"x-kubernetes-int-or-string": True}
OPEN = {"type": "object", "x-kubernetes-preserve-unknown-fields": True}


def obj(props: Dict[str, Any], required: Sequence[str] = ()) -> Dict[str, Any]:
    out: Dict[str, Any] = {"type": "object", "properties": props}
    if required:
        out["required"] = list(required)
    return out


def arr(item: Dict[str, Any]) -> Dict[str, Any]:
    return {"type": "array", "items": item}


def strmap() -> Dict[str, Any]:
    return {"type": "object", "additionalProperties": STR}


def qmap() -> Dict[str, Any]:
    """Quantity map (resource requests/limits): values like "4" / "16Gi"."""
    return {"type": "object", "additionalProperties": INT_OR_STR}


def enum(*vals: str) -> Dict[str, Any]:
    return {"type": "string", "enum": list(vals)}


# -- shared building blocks -------------------------------------------------

OWNER_REF = obj(
    {
        "apiVersion": STR, "kind": STR, "name": STR, "uid": STR,
        "controller": BOOL, "blockOwnerDeletion": BOOL,
    },
    required=("apiVersion", "kind", "name", "uid"),
)

METADATA = obj(
    {
        "name": STR, "generateName": STR, "namespace": STR,
        "labels": strmap(), "annotations": strmap(),
        "uid": STR, "resourceVersion": STR, "generation": INT,
        "creationTimestamp": STR, "deletionTimestamp": STR,
        "deletionGracePeriodSeconds": INT,
        "finalizers": arr(STR),
        "ownerReferences": arr(OWNER_REF),
        "managedFields": arr(OPEN),
    }
)

CONDITION = obj(
    {
        "type": STR,
        "status": enum("True", "False", "Unknown"),
        "reason": STR, "message": STR,
        "lastTransitionTime": STR, "lastProbeTime": STR,
        "lastUpdateTime": STR, "observedGeneration": INT,
    },
    required=("type", "status"),
)

LABEL_SELECTOR = obj(
    {
        "matchLabels": strmap(),
        "matchExpressions": arr(
            obj(
                {
                    "key": STR,
                    "operator": enum("In", "NotIn", "Exists", "DoesNotExist"),
                    "values": arr(STR),
                },
                required=("key", "operator"),
            )
        ),
    }
)

ENV_VAR = obj(
    {
        "name": STR,
        "value": STR,
        "valueFrom": obj(
            {
                "secretKeyRef": obj(
                    {"name": STR, "key": STR, "optional": BOOL},
                    required=("key",),
                ),
                "configMapKeyRef": obj(
                    {"name": STR, "key": STR, "optional": BOOL},
                    required=("key",),
                ),
                "fieldRef": obj(
                    {"apiVersion": STR, "fieldPath": STR},
                    required=("fieldPath",),
                ),
                "resourceFieldRef": obj(
                    {"containerName": STR, "resource": STR,
                     "divisor": INT_OR_STR},
                    required=("resource",),
                ),
            }
        ),
    },
    required=("name",),
)

PROBE = obj(
    {
        "httpGet": obj(
            {
                "path": STR, "port": INT_OR_STR, "host": STR,
                "scheme": enum("HTTP", "HTTPS"),
                "httpHeaders": arr(
                    obj({"name": STR, "value": STR},
                        required=("name", "value"))
                ),
            },
            required=("port",),
        ),
        "tcpSocket": obj({"port": INT_OR_STR, "host": STR},
                         required=("port",)),
        "exec": obj({"command": arr(STR)}),
        "grpc": obj({"port": INT, "service": STR}, required=("port",)),
        "initialDelaySeconds": INT, "periodSeconds": INT,
        "timeoutSeconds": INT, "successThreshold": INT,
        "failureThreshold": INT, "terminationGracePeriodSeconds": INT,
    }
)

CONTAINER = obj(
    {
        "name": STR, "image": STR,
        "command": arr(STR), "args": arr(STR),
        "workingDir": STR,
        "env": arr(ENV_VAR),
        "envFrom": arr(
            obj(
                {
                    "prefix": STR,
                    "configMapRef": obj({"name": STR, "optional": BOOL}),
                    "secretRef": obj({"name": STR, "optional": BOOL}),
                }
            )
        ),
        "ports": arr(
            obj(
                {
                    "containerPort": INT, "name": STR, "hostPort": INT,
                    "hostIP": STR, "protocol": enum("TCP", "UDP", "SCTP"),
                },
                required=("containerPort",),
            )
        ),
        "resources": obj(
            {"requests": qmap(), "limits": qmap(),
             "claims": arr(obj({"name": STR}, required=("name",)))}
        ),
        "volumeMounts": arr(
            obj(
                {
                    "name": STR, "mountPath": STR, "subPath": STR,
                    "subPathExpr": STR, "readOnly": BOOL,
                    "mountPropagation": STR,
                },
                required=("name", "mountPath"),
            )
        ),
        "volumeDevices": arr(
            obj({"name": STR, "devicePath": STR},
                required=("name", "devicePath"))
        ),
        "readinessProbe": PROBE, "livenessProbe": PROBE,
        "startupProbe": PROBE,
        "lifecycle": OPEN, "securityContext": OPEN,
        "imagePullPolicy": enum("Always", "IfNotPresent", "Never"),
        "stdin": BOOL, "stdinOnce": BOOL, "tty": BOOL,
        "terminationMessagePath": STR,
        "terminationMessagePolicy": STR,
        "restartPolicy": enum("Always"),  # sidecar init containers
    },
    required=("name",),
)

KEY_TO_PATH = obj(
    {"key": STR, "path": STR, "mode": INT}, required=("key", "path")
)

VOLUME = obj(
    {
        "name": STR,
        "configMap": obj(
            {"name": STR, "items": arr(KEY_TO_PATH), "defaultMode": INT,
             "optional": BOOL}
        ),
        "secret": obj(
            {"secretName": STR, "items": arr(KEY_TO_PATH),
             "defaultMode": INT, "optional": BOOL}
        ),
        "emptyDir": obj({"medium": STR, "sizeLimit": INT_OR_STR}),
        "hostPath": obj({"path": STR, "type": STR}, required=("path",)),
        "persistentVolumeClaim": obj(
            {"claimName": STR, "readOnly": BOOL}, required=("claimName",)
        ),
        "csi": obj(
            {
                "driver": STR, "readOnly": BOOL, "fsType": STR,
                "volumeAttributes": strmap(),
                "nodePublishSecretRef": obj({"name": STR}),
            },
            required=("driver",),
        ),
        "downwardAPI": OPEN,
        "projected": OPEN,
    },
    required=("name",),
)

TOLERATION = obj(
    {
        "key": STR,
        "operator": enum("Exists", "Equal"),
        "value": STR,
        "effect": enum("NoSchedule", "PreferNoSchedule", "NoExecute"),
        "tolerationSeconds": INT,
    }
)

POD_SPEC = obj(
    {
        "containers": arr(CONTAINER),
        "initContainers": arr(CONTAINER),
        "ephemeralContainers": arr(OPEN),
        "volumes": arr(VOLUME),
        "restartPolicy": enum("Always", "OnFailure", "Never"),
        "serviceAccountName": STR, "serviceAccount": STR,
        "automountServiceAccountToken": BOOL,
        "nodeSelector": strmap(),
        "nodeName": STR,
        "tolerations": arr(TOLERATION),
        "affinity": OPEN,
        "topologySpreadConstraints": arr(OPEN),
        "hostNetwork": BOOL, "hostPID": BOOL, "hostIPC": BOOL,
        "shareProcessNamespace": BOOL,
        "hostname": STR, "subdomain": STR, "setHostnameAsFQDN": BOOL,
        "securityContext": OPEN,
        "imagePullSecrets": arr(obj({"name": STR})),
        "terminationGracePeriodSeconds": INT,
        "activeDeadlineSeconds": INT,
        "dnsPolicy": STR, "dnsConfig": OPEN,
        "priorityClassName": STR, "priority": INT,
        "preemptionPolicy": STR,
        "schedulerName": STR, "schedulingGates": arr(OPEN),
        "runtimeClassName": STR,
        "enableServiceLinks": BOOL,
        "overhead": qmap(),
        "os": obj({"name": enum("linux", "windows")}, required=("name",)),
        "hostAliases": arr(OPEN),
        "readinessGates": arr(OPEN),
        "resourceClaims": arr(OPEN),
    },
    required=("containers",),
)

POD_TEMPLATE = obj({"metadata": METADATA, "spec": POD_SPEC})

POD_STATUS = obj(
    {
        "phase": enum("Pending", "Running", "Succeeded", "Failed", "Unknown"),
        "conditions": arr(CONDITION),
        "message": STR, "reason": STR,
        "hostIP": STR, "hostIPs": arr(obj({"ip": STR})),
        "podIP": STR, "podIPs": arr(obj({"ip": STR})),
        "startTime": STR,
        "containerStatuses": arr(OPEN),
        "initContainerStatuses": arr(OPEN),
        "ephemeralContainerStatuses": arr(OPEN),
        "qosClass": STR, "nominatedNodeName": STR, "resize": STR,
    }
)

JOB_SPEC = obj(
    {
        "template": POD_TEMPLATE,
        "parallelism": INT, "completions": INT,
        "completionMode": enum("NonIndexed", "Indexed"),
        "backoffLimit": INT, "backoffLimitPerIndex": INT,
        "maxFailedIndexes": INT,
        "activeDeadlineSeconds": INT, "ttlSecondsAfterFinished": INT,
        "suspend": BOOL, "manualSelector": BOOL,
        "selector": LABEL_SELECTOR,
        "podFailurePolicy": OPEN,
        "successPolicy": OPEN,
        "podReplacementPolicy": STR,
    },
    required=("template",),
)

JOB_STATUS = obj(
    {
        "conditions": arr(CONDITION),
        "active": INT, "succeeded": INT, "failed": INT, "ready": INT,
        "terminating": INT,
        "startTime": STR, "completionTime": STR,
        "completedIndexes": STR, "failedIndexes": STR,
        "uncountedTerminatedPods": OPEN,
    }
)

DEPLOYMENT_SPEC = obj(
    {
        "replicas": INT,
        "selector": LABEL_SELECTOR,
        "template": POD_TEMPLATE,
        "strategy": obj(
            {
                "type": enum("Recreate", "RollingUpdate"),
                "rollingUpdate": obj(
                    {"maxSurge": INT_OR_STR, "maxUnavailable": INT_OR_STR}
                ),
            }
        ),
        "minReadySeconds": INT, "revisionHistoryLimit": INT,
        "progressDeadlineSeconds": INT, "paused": BOOL,
    },
    required=("selector", "template"),
)

DEPLOYMENT_STATUS = obj(
    {
        "replicas": INT, "readyReplicas": INT, "availableReplicas": INT,
        "unavailableReplicas": INT, "updatedReplicas": INT,
        "observedGeneration": INT, "collisionCount": INT,
        "conditions": arr(CONDITION),
    }
)

SERVICE_SPEC = obj(
    {
        "selector": strmap(),
        "ports": arr(
            obj(
                {
                    "port": INT, "targetPort": INT_OR_STR, "name": STR,
                    "protocol": enum("TCP", "UDP", "SCTP"),
                    "nodePort": INT, "appProtocol": STR,
                },
                required=("port",),
            )
        ),
        "clusterIP": STR, "clusterIPs": arr(STR),
        "type": enum("ClusterIP", "NodePort", "LoadBalancer", "ExternalName"),
        "sessionAffinity": enum("None", "ClientIP"),
        "sessionAffinityConfig": OPEN,
        "externalName": STR,
        "externalIPs": arr(STR),
        "externalTrafficPolicy": enum("Cluster", "Local"),
        "internalTrafficPolicy": enum("Cluster", "Local"),
        "ipFamilies": arr(STR), "ipFamilyPolicy": STR,
        "publishNotReadyAddresses": BOOL,
        "loadBalancerIP": STR, "loadBalancerClass": STR,
        "loadBalancerSourceRanges": arr(STR),
        "allocateLoadBalancerNodePorts": BOOL,
        "healthCheckNodePort": INT,
        "trafficDistribution": STR,
    }
)

SERVICE_STATUS = obj(
    {"loadBalancer": OPEN, "conditions": arr(CONDITION)}
)

LEASE_SPEC = obj(
    {
        "holderIdentity": STR, "leaseDurationSeconds": INT,
        "acquireTime": STR, "renewTime": STR, "leaseTransitions": INT,
        "strategy": STR, "preferredHolder": STR,
    }
)

# JobSet (jobset.x-k8s.io/v1alpha2) — field names per the upstream JobSet
# API reference; the gang-scheduling story (controller/workloads.py::
# jobset_from_pod, tests/test_gang_failure.py) emits and fakes exactly
# these shapes, so a typo here or there now fails the suite.
JOBSET_SPEC = obj(
    {
        "replicatedJobs": arr(
            obj(
                {
                    "name": STR,
                    "replicas": INT,
                    "groupName": STR,
                    "template": obj(
                        {"metadata": METADATA, "spec": JOB_SPEC},
                        required=("spec",),
                    ),
                    "dependsOn": arr(
                        obj(
                            {"name": STR,
                             "status": enum("Ready", "Complete")},
                            required=("name", "status"),
                        )
                    ),
                },
                required=("name", "template"),
            )
        ),
        "failurePolicy": obj(
            {
                "maxRestarts": INT,
                "restartStrategy": enum("Recreate", "BlockingRecreate"),
                "rules": arr(
                    obj(
                        {
                            "name": STR,
                            "action": enum(
                                "FailJobSet", "RestartJobSet",
                                "RestartJobSetAndIgnoreMaxRestarts",
                            ),
                            "onJobFailureReasons": arr(STR),
                            "targetReplicatedJobs": arr(STR),
                        },
                        required=("name", "action"),
                    )
                ),
            }
        ),
        "successPolicy": obj(
            {"operator": enum("All", "Any"),
             "targetReplicatedJobs": arr(STR)},
            required=("operator",),
        ),
        "startupPolicy": obj(
            {"startupPolicyOrder": enum("AnyOrder", "InOrder")},
            required=("startupPolicyOrder",),
        ),
        "network": obj(
            {
                "enableDNSHostnames": BOOL, "subdomain": STR,
                "publishNotReadyAddresses": BOOL,
            }
        ),
        "suspend": BOOL,
        "managedBy": STR,
        "ttlSecondsAfterFinished": INT,
        "coordinator": obj(
            {"replicatedJob": STR, "jobIndex": INT, "podIndex": INT},
            required=("replicatedJob",),
        ),
    },
    required=("replicatedJobs",),
)

JOBSET_STATUS = obj(
    {
        "conditions": arr(CONDITION),
        "restarts": INT, "restartsCountTowardsMax": INT,
        "terminalState": STR,
        "replicatedJobsStatus": arr(
            obj(
                {
                    "name": STR, "ready": INT, "succeeded": INT,
                    "failed": INT, "active": INT, "suspended": INT,
                },
                required=("name",),
            )
        ),
        "individualJobRecreates": {"type": "object",
                                   "additionalProperties": INT},
    }
)


RBAC_RULE = obj(
    {
        "apiGroups": arr(STR), "resources": arr(STR), "verbs": arr(STR),
        "resourceNames": arr(STR), "nonResourceURLs": arr(STR),
    },
    required=("verbs",),
)

RBAC_SUBJECT = obj(
    {"kind": STR, "name": STR, "namespace": STR, "apiGroup": STR},
    required=("kind", "name"),
)

RBAC_ROLE_REF = obj(
    {"apiGroup": STR, "kind": STR, "name": STR}, required=("kind", "name")
)

DAEMONSET_SPEC = obj(
    {
        "selector": LABEL_SELECTOR,
        "template": POD_TEMPLATE,
        "updateStrategy": OPEN,
        "minReadySeconds": INT,
        "revisionHistoryLimit": INT,
    },
    required=("selector", "template"),
)


def _sections(spec: Optional[Dict] = None, status: Optional[Dict] = None,
              **extra: Dict) -> Dict[str, Any]:
    props: Dict[str, Any] = {}
    if spec is not None:
        props["spec"] = spec
    if status is not None:
        props["status"] = status
    props.update(extra)
    return props


# kind -> (expected apiVersion, section schemas). Everything FakeKube
# stores must appear here; an unlisted kind is itself an error.
REGISTRY: Dict[str, Tuple[str, Dict[str, Any]]] = {
    "Pod": ("v1", _sections(POD_SPEC, POD_STATUS)),
    "Service": ("v1", _sections(SERVICE_SPEC, SERVICE_STATUS)),
    "ConfigMap": (
        "v1",
        _sections(data=strmap(), binaryData=strmap(), immutable=BOOL),
    ),
    "Secret": (
        "v1",
        _sections(data=strmap(), stringData=strmap(), binaryData=strmap(),
                  type=STR, immutable=BOOL),
    ),
    "ServiceAccount": (
        "v1",
        _sections(
            secrets=arr(obj({"name": STR})),
            imagePullSecrets=arr(obj({"name": STR})),
            automountServiceAccountToken=BOOL,
        ),
    ),
    "Job": ("batch/v1", _sections(JOB_SPEC, JOB_STATUS)),
    "Deployment": ("apps/v1", _sections(DEPLOYMENT_SPEC, DEPLOYMENT_STATUS)),
    "JobSet": ("jobset.x-k8s.io/v1alpha2", _sections(JOBSET_SPEC,
                                                     JOBSET_STATUS)),
    "Lease": ("coordination.k8s.io/v1", _sections(LEASE_SPEC)),
    # core/v1 Event (flat top-level fields, no spec/status): the
    # controller event stream (observability/events.py) upserts these so
    # `sub events` / `kubectl get events` narrate reconcile transitions.
    "Event": (
        "v1",
        _sections(
            involvedObject=obj(
                {
                    "apiVersion": STR, "kind": STR, "namespace": STR,
                    "name": STR, "uid": STR, "resourceVersion": STR,
                    "fieldPath": STR,
                }
            ),
            reason=STR,
            message=STR,
            type=enum("Normal", "Warning"),
            count=INT,
            firstTimestamp=STR,
            lastTimestamp=STR,
            eventTime=STR,
            action=STR,
            source=obj({"component": STR, "host": STR}),
            reportingComponent=STR,
            reportingInstance=STR,
            related=OPEN,
            series=OPEN,
        ),
    ),
    # Installed by `sub`/install manifests; apiextensions validation is the
    # apiserver's job, not a controller-emission surface — keep it open.
    "CustomResourceDefinition": ("apiextensions.k8s.io/v1",
                                 _sections(OPEN, OPEN)),
    # Install/config-manifest kinds (install/substratus-tpu.yaml,
    # config/*): validated by tests/test_install_manifests.py so a typo
    # in the shipped YAML fails CI instead of a live kubectl apply.
    "Namespace": ("v1", _sections(obj({"finalizers": arr(STR)}), OPEN)),
    "ClusterRole": (
        "rbac.authorization.k8s.io/v1",
        _sections(rules=arr(RBAC_RULE), aggregationRule=OPEN),
    ),
    "ClusterRoleBinding": (
        "rbac.authorization.k8s.io/v1",
        _sections(subjects=arr(RBAC_SUBJECT), roleRef=RBAC_ROLE_REF),
    ),
    "Role": (
        "rbac.authorization.k8s.io/v1", _sections(rules=arr(RBAC_RULE))
    ),
    "RoleBinding": (
        "rbac.authorization.k8s.io/v1",
        _sections(subjects=arr(RBAC_SUBJECT), roleRef=RBAC_ROLE_REF),
    ),
    "DaemonSet": ("apps/v1", _sections(DAEMONSET_SPEC, OPEN)),
    # Prometheus-operator CRD: not a core type; shape is the operator's
    # contract, keep open like CustomResourceDefinition.
    "ServiceMonitor": ("monitoring.coreos.com/v1", _sections(OPEN)),
}


def _load_crd_schemas() -> None:
    """Register the substratus.ai kinds from the same crdgen output that
    `make manifests` ships — the validator enforces exactly the CRDs a
    real apiserver would."""
    from substratus_tpu.api import crdgen, types as T

    for kind in T.KINDS:
        crd = crdgen.crd_for(kind)
        version = crd["spec"]["versions"][0]
        root = version["schema"]["openAPIV3Schema"]
        REGISTRY[kind] = (
            f"{T.GROUP}/{version['name']}", root.get("properties", {})
        )


_load_crd_schemas()


def _fmt(path: List[str]) -> str:
    return ".".join(path) or "<root>"


def _check(value: Any, schema: Dict[str, Any], path: List[str]) -> None:
    if value is None:
        return  # JSON serialization drops nulls; null == absent
    if schema.get("x-kubernetes-int-or-string"):
        if not isinstance(value, (int, str)) or isinstance(value, bool):
            raise SchemaError(f"{_fmt(path)}: expected int-or-string, got "
                              f"{type(value).__name__}")
        return
    t = schema.get("type")
    if t == "object":
        if not isinstance(value, dict):
            raise SchemaError(
                f"{_fmt(path)}: expected object, got {type(value).__name__}"
            )
        if schema.get("x-kubernetes-preserve-unknown-fields"):
            return
        props = schema.get("properties")
        addl = schema.get("additionalProperties")
        for req in schema.get("required", ()):
            if value.get(req) is None:
                raise SchemaError(f"{_fmt(path)}: missing required field "
                                  f"{req!r}")
        for k, v in value.items():
            if props is not None and k in props:
                _check(v, props[k], path + [k])
            elif addl is not None:
                _check(v, addl, path + [k])
            elif props is not None:
                known = ", ".join(sorted(props)[:12])
                raise SchemaError(
                    f"{_fmt(path)}: unknown field {k!r} (known: {known})"
                )
        return
    if t == "array":
        if not isinstance(value, list):
            raise SchemaError(
                f"{_fmt(path)}: expected array, got {type(value).__name__}"
            )
        item = schema.get("items", OPEN)
        for i, v in enumerate(value):
            _check(v, item, path + [f"[{i}]"])
        return
    if t == "string":
        if not isinstance(value, str):
            raise SchemaError(
                f"{_fmt(path)}: expected string, got {type(value).__name__}"
            )
        if "enum" in schema and value not in schema["enum"]:
            raise SchemaError(
                f"{_fmt(path)}: {value!r} not one of {schema['enum']}"
            )
        return
    if t == "integer":
        if not isinstance(value, int) or isinstance(value, bool):
            raise SchemaError(
                f"{_fmt(path)}: expected integer, got {type(value).__name__}"
            )
        return
    if t == "number":
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise SchemaError(
                f"{_fmt(path)}: expected number, got {type(value).__name__}"
            )
        return
    if t == "boolean":
        if not isinstance(value, bool):
            raise SchemaError(
                f"{_fmt(path)}: expected boolean, got {type(value).__name__}"
            )
        return
    # no type: open


def validate(obj_: Dict[str, Any]) -> None:
    """Validate a full manifest: apiVersion/kind pair, metadata, and every
    non-meta section against the registered schema. Raises SchemaError."""
    kind = obj_.get("kind")
    if not isinstance(kind, str) or not kind:
        raise SchemaError("manifest has no kind")
    if kind not in REGISTRY:
        raise SchemaError(f"no schema registered for kind {kind!r} — add it "
                          "to kube/schema.py REGISTRY")
    want_api, sections = REGISTRY[kind]
    api = obj_.get("apiVersion")
    if api != want_api:
        raise SchemaError(
            f"{kind}: apiVersion {api!r} != expected {want_api!r}"
        )
    md = obj_.get("metadata")
    if not isinstance(md, dict) or not md.get("name"):
        raise SchemaError(f"{kind}: metadata.name is required")
    _check(md, METADATA, ["metadata"])
    for key, val in obj_.items():
        if key in ("apiVersion", "kind", "metadata"):
            continue
        if key not in sections:
            known = ", ".join(sorted(sections))
            raise SchemaError(
                f"{kind}: unknown top-level section {key!r} (known: {known})"
            )
        _check(val, sections[key], [key])
