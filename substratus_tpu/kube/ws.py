"""Minimal RFC 6455 WebSocket client for the Kubernetes streaming APIs.

The reference reaches pods through client-go's SPDY/WebSocket executor
(internal/client/sync.go:137-176, port_forward.go:21-44). Kubernetes has
supported WebSocket transports for exec/attach/port-forward since long
before SPDY's deprecation, and a WebSocket client is small enough to own:
this module implements the client half of RFC 6455 over the stdlib
(http/ssl sockets) — handshake, masked client frames, fragmented reads,
ping/pong/close — plus the two K8s subprotocols built on it:

* `v4.channel.k8s.io` (exec/attach): every binary message is prefixed
  with one channel byte — 0 stdin, 1 stdout, 2 stderr, 3 error/status,
  4 resize.
* `portforward.k8s.io`: stream pairs per forwarded port — even channel
  data, odd channel error; each stream's first message is the port
  number (2 bytes little-endian).

No external websocket dependency, no kubectl subprocess.
"""
from __future__ import annotations

import base64
import json
import os
import socket
import ssl
import struct
import threading
from typing import Iterator, Optional, Tuple
from urllib.parse import urlsplit

# K8s channel-protocol channel ids (v4.channel.k8s.io)
STDIN, STDOUT, STDERR, ERROR, RESIZE = 0, 1, 2, 3, 4

_OP_TEXT, _OP_BINARY, _OP_CLOSE, _OP_PING, _OP_PONG = 0x1, 0x2, 0x8, 0x9, 0xA


class WSError(RuntimeError):
    pass


def _mask_xor(payload: bytes, mask: bytes) -> bytes:
    """XOR `payload` with the repeating 4-byte mask, in bulk (one bignum
    XOR, ~GB/s) — per-byte Python loops cap exec/port-forward throughput
    at a few MB/s."""
    n = len(payload)
    if n == 0:
        return b""
    reps = mask * ((n + 3) // 4)
    x = int.from_bytes(payload, "little") ^ int.from_bytes(reps[:n], "little")
    return x.to_bytes(n, "little")


class WebSocket:
    """One client WebSocket connection (blocking I/O)."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = bytearray()
        self.closed = False
        # One frame at a time on the wire: recv() replies to PING/CLOSE
        # from whatever thread is reading while another thread sends data
        # (port-forward does exactly this); interleaved sendall bytes would
        # desync the server's frame parser.
        self._send_lock = threading.Lock()

    # -- connection -------------------------------------------------------

    @classmethod
    def connect(
        cls,
        url: str,
        *,
        headers: Optional[dict] = None,
        subprotocols: Tuple[str, ...] = (),
        ssl_context: Optional[ssl.SSLContext] = None,
        timeout: float = 30.0,
    ) -> "WebSocket":
        """Open and upgrade. `url` is https:// or wss:// (or http/ws)."""
        parts = urlsplit(url)
        tls = parts.scheme in ("https", "wss")
        port = parts.port or (443 if tls else 80)
        raw = socket.create_connection((parts.hostname, port), timeout=timeout)
        if tls:
            ctx = ssl_context or ssl.create_default_context()
            raw = ctx.wrap_socket(raw, server_hostname=parts.hostname)

        key = base64.b64encode(os.urandom(16)).decode()
        path = parts.path + (f"?{parts.query}" if parts.query else "")
        lines = [
            f"GET {path or '/'} HTTP/1.1",
            f"Host: {parts.hostname}:{port}",
            "Upgrade: websocket",
            "Connection: Upgrade",
            f"Sec-WebSocket-Key: {key}",
            "Sec-WebSocket-Version: 13",
        ]
        if subprotocols:
            lines.append(
                "Sec-WebSocket-Protocol: " + ", ".join(subprotocols)
            )
        for name, value in (headers or {}).items():
            lines.append(f"{name}: {value}")
        raw.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())

        # Read the upgrade response head.
        head = b""
        while b"\r\n\r\n" not in head:
            chunk = raw.recv(4096)
            if not chunk:
                raise WSError("connection closed during handshake")
            head += chunk
            if len(head) > 65536:
                raise WSError("oversized handshake response")
        head, rest = head.split(b"\r\n\r\n", 1)
        status = head.split(b"\r\n", 1)[0].decode(errors="replace")
        if " 101 " not in status + " ":
            body = rest[:300].decode(errors="replace")
            raise WSError(f"upgrade refused: {status} {body}")
        # The timeout guarded the handshake only: exec/port-forward streams
        # legitimately idle far longer than any fixed timeout.
        raw.settimeout(None)
        ws = cls(raw)
        ws._buf = bytearray(rest)
        return ws

    # -- frames -----------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise WSError("connection closed mid-frame")
            self._buf += chunk  # bytearray: amortized append, no re-copy
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out

    def send(self, payload: bytes, opcode: int = _OP_BINARY) -> None:
        """Send one masked frame (clients MUST mask, RFC 6455 §5.3)."""
        mask = os.urandom(4)
        n = len(payload)
        head = bytes([0x80 | opcode])
        if n < 126:
            head += bytes([0x80 | n])
        elif n < 65536:
            head += bytes([0x80 | 126]) + struct.pack(">H", n)
        else:
            head += bytes([0x80 | 127]) + struct.pack(">Q", n)
        masked = _mask_xor(payload, mask)
        with self._send_lock:
            self._sock.sendall(head + mask + masked)

    def recv(self) -> Optional[bytes]:
        """Next complete message payload; None once the peer closes.
        Ping/pong handled internally; fragmented messages reassembled."""
        message = b""
        while True:
            b1, b2 = self._read_exact(2)
            fin, opcode = b1 & 0x80, b1 & 0x0F
            masked, n = b2 & 0x80, b2 & 0x7F
            if n == 126:
                (n,) = struct.unpack(">H", self._read_exact(2))
            elif n == 127:
                (n,) = struct.unpack(">Q", self._read_exact(8))
            mask = self._read_exact(4) if masked else b""
            payload = self._read_exact(n)
            if mask:
                payload = _mask_xor(payload, mask)
            if opcode == _OP_PING:
                self.send(payload, _OP_PONG)
                continue
            if opcode == _OP_PONG:
                continue
            if opcode == _OP_CLOSE:
                if not self.closed:
                    self.closed = True
                    try:
                        self.send(payload[:2], _OP_CLOSE)
                    except OSError:
                        pass
                return None
            message += payload
            if fin:
                return message

    def messages(self) -> Iterator[bytes]:
        while True:
            m = self.recv()
            if m is None:
                return
            yield m

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                self.send(struct.pack(">H", 1000), _OP_CLOSE)
            except OSError:
                pass
        try:
            self._sock.close()
        except OSError:
            pass


class ExecStream:
    """`v4.channel.k8s.io` channel demux over one WebSocket (exec/attach)."""

    def __init__(self, ws: WebSocket):
        self.ws = ws

    def send_stdin(self, data: bytes) -> None:
        self.ws.send(bytes([STDIN]) + data)

    def chunks(self) -> Iterator[Tuple[int, bytes]]:
        """Yield (channel, data) pairs until the server closes."""
        for msg in self.ws.messages():
            if not msg:
                continue
            yield msg[0], msg[1:]

    def run(self) -> Tuple[bytes, bytes, dict]:
        """Drain to completion -> (stdout, stderr, status). status is the
        V1Status JSON from the error channel ({} means success)."""
        out, err, status = b"", b"", {}
        for channel, data in self.chunks():
            if channel == STDOUT:
                out += data
            elif channel == STDERR:
                err += data
            elif channel == ERROR:
                try:
                    status = json.loads(data)
                except json.JSONDecodeError:
                    status = {"status": "Failure",
                              "message": data.decode(errors="replace")}
        self.ws.close()
        return out, err, status

    def close(self) -> None:
        self.ws.close()


class PortForwardStream:
    """`portforward.k8s.io` single-port stream pair over one WebSocket.

    K8s sends each stream's port announcement (2 bytes LE) as the first
    message on channels 0 (data) and 1 (error); afterwards channel 0
    carries the TCP bytes both ways.
    """

    def __init__(self, ws: WebSocket):
        self.ws = ws
        self._announced: set = set()

    def send(self, data: bytes) -> None:
        self.ws.send(b"\x00" + data)

    def chunks(self) -> Iterator[bytes]:
        """Yield remote->local data chunks (announcements skipped, error
        channel raises)."""
        for msg in self.ws.messages():
            if not msg:
                continue
            channel, data = msg[0], msg[1:]
            if channel not in self._announced:
                self._announced.add(channel)  # port announcement frame
                continue
            if channel == 1 and data:
                raise WSError(f"port-forward: {data.decode(errors='replace')}")
            if channel == 0:
                yield data

    def close(self) -> None:
        self.ws.close()
