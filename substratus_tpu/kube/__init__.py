from substratus_tpu.kube.client import KubeClient, NotFound, Conflict
from substratus_tpu.kube.fake import FakeKube

__all__ = ["KubeClient", "FakeKube", "NotFound", "Conflict"]
