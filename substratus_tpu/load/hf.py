"""HuggingFace checkpoint import -> substratus_tpu params.

This is the in-repo replacement for the reference's external
`substratusai/model-loader-huggingface` image (SURVEY.md §2.2;
examples/llama2-7b/base-model.yaml:7): it turns HF Llama-family weights
(safetensors) into the framework's stacked-layer pytree, ready to be sharded
onto a mesh and/or written to `/content/artifacts` as an Orbax checkpoint
(train/checkpoints.py).

Weight-layout notes: HF Linear stores [out, in]; we store [in, ...out] so the
forward pass is `x @ w` without transposes. RoPE uses the HF rotate-half
convention (ops/basics.py), so no head permutation is needed.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from substratus_tpu.models.llama import CONFIGS, LlamaConfig, Params


def config_from_hf(hf_cfg: Any) -> LlamaConfig:
    """Map a transformers Llama/Mistral/MixtralConfig(-like) to LlamaConfig."""
    get = lambda name, default=None: getattr(hf_cfg, name, default)
    return LlamaConfig(
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=get("num_key_value_heads") or hf_cfg.num_attention_heads,
        hidden_dim=hf_cfg.intermediate_size,
        head_dim=get("head_dim"),
        rope_theta=get("rope_theta", 10000.0),
        norm_eps=get("rms_norm_eps", 1e-5),
        max_seq_len=get("max_position_embeddings", 4096),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
        # Mixtral MoE fields
        n_experts=get("num_local_experts", 0) or 0,
        n_experts_per_token=get("num_experts_per_tok", 2) or 2,
        router_aux_weight=get("router_aux_loss_coef", 0.01) or 0.01,
    )


def _np(t: Any) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        t = t.detach().to("cpu").float().numpy()
    return np.asarray(t)


def convert_llama_state_dict(
    sd: Mapping[str, Any], cfg: LlamaConfig, dtype=jnp.bfloat16
) -> Params:
    """HF Llama state dict -> stacked-layer params pytree."""
    hd = cfg.head_size
    L, D, H, KH, M = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads, cfg.hidden_dim

    def get(name: str) -> np.ndarray:
        for prefix in ("", "model."):
            if prefix + name in sd:
                return _np(sd[prefix + name])
        raise KeyError(name)

    def stack(fmt: str, transform) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([transform(get(fmt.format(i=i))) for i in range(L)]), dtype
        )

    params: Params = {
        "tok_embed": jnp.asarray(get("embed_tokens.weight"), dtype),
        "layers": {
            "attn_norm": stack("layers.{i}.input_layernorm.weight", lambda w: w),
            "wq": stack(
                "layers.{i}.self_attn.q_proj.weight",
                lambda w: w.T.reshape(D, H, hd),
            ),
            "wk": stack(
                "layers.{i}.self_attn.k_proj.weight",
                lambda w: w.T.reshape(D, KH, hd),
            ),
            "wv": stack(
                "layers.{i}.self_attn.v_proj.weight",
                lambda w: w.T.reshape(D, KH, hd),
            ),
            "wo": stack(
                "layers.{i}.self_attn.o_proj.weight",
                lambda w: w.T.reshape(H, hd, D),
            ),
            "mlp_norm": stack("layers.{i}.post_attention_layernorm.weight", lambda w: w),
        },
        "out_norm": jnp.asarray(get("norm.weight"), dtype),
    }
    if cfg.n_experts > 0:
        # Mixtral MoE: block_sparse_moe.gate -> router, experts.N.{w1,w3,w2}
        # -> gate/up/down stacked on a leading expert dim.
        E = cfg.n_experts

        def stack_experts(w_name: str, transform) -> jnp.ndarray:
            # Convert expert-by-expert straight into the target dtype: a
            # whole-tensor float32 numpy transient would be ~60 GB for
            # mixtral-8x7b ([32,8,4096,14336] f32) on top of the resident
            # state dict.
            per_layer = []
            for i in range(L):
                per_layer.append(
                    jnp.stack(
                        [
                            jnp.asarray(
                                transform(
                                    get(
                                        f"layers.{i}.block_sparse_moe."
                                        f"experts.{e}.{w_name}.weight"
                                    )
                                ),
                                dtype,
                            )
                            for e in range(E)
                        ]
                    )
                )
            return jnp.stack(per_layer)

        params["layers"].update(
            {
                "router": stack(
                    "layers.{i}.block_sparse_moe.gate.weight", lambda w: w.T
                ),
                "w_gate": stack_experts("w1", lambda w: w.T),
                "w_up": stack_experts("w3", lambda w: w.T),
                "w_down": stack_experts("w2", lambda w: w.T),
            }
        )
    else:
        params["layers"].update(
            {
                "w_gate": stack("layers.{i}.mlp.gate_proj.weight", lambda w: w.T),
                "w_up": stack("layers.{i}.mlp.up_proj.weight", lambda w: w.T),
                "w_down": stack("layers.{i}.mlp.down_proj.weight", lambda w: w.T),
            }
        )
    if not cfg.tie_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dtype)
    return params


def config_from_hf_opt(hf_cfg: Any):
    from substratus_tpu.models.opt import OPTConfig

    # Architecture variants models/opt.py does not implement; fail loudly
    # rather than convert to silently-wrong logits (opt-350m is post-LN with
    # a projected embedding dim).
    if not getattr(hf_cfg, "do_layer_norm_before", True):
        raise NotImplementedError(
            "post-LN OPT variants (do_layer_norm_before=false, e.g. "
            "opt-350m) are not supported"
        )
    act = getattr(hf_cfg, "activation_function", "relu")
    if act != "relu":
        raise NotImplementedError(
            f"OPT activation {act!r} not supported (e.g. Galactica uses "
            "gelu); models/opt.py implements relu"
        )
    proj = getattr(hf_cfg, "word_embed_proj_dim", hf_cfg.hidden_size)
    if proj != hf_cfg.hidden_size:
        raise NotImplementedError(
            f"OPT word_embed_proj_dim={proj} != hidden_size="
            f"{hf_cfg.hidden_size} (embedding projection) is not supported"
        )
    return OPTConfig(
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        hidden_dim=hf_cfg.ffn_dim,
        max_seq_len=hf_cfg.max_position_embeddings,
    )


def convert_opt_state_dict(sd: Mapping[str, Any], cfg, dtype=jnp.bfloat16) -> Params:
    """HF OPTForCausalLM state dict -> models/opt.py params. Note HF's
    per-layer `final_layer_norm` is the pre-FFN norm (ln2 here); the
    top-level decoder final_layer_norm is the real final norm."""
    hd = cfg.head_size
    L, D, H = cfg.n_layers, cfg.dim, cfg.n_heads

    def get(name: str) -> np.ndarray:
        for prefix in ("model.decoder.", "decoder.", ""):
            if prefix + name in sd:
                return _np(sd[prefix + name])
        raise KeyError(name)

    def stack(fmt: str, transform) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([transform(get(fmt.format(i=i))) for i in range(L)]), dtype
        )

    return {
        "tok_embed": jnp.asarray(get("embed_tokens.weight"), dtype),
        "pos_embed": jnp.asarray(get("embed_positions.weight"), dtype),
        "layers": {
            "ln1_scale": stack("layers.{i}.self_attn_layer_norm.weight", lambda w: w),
            "ln1_bias": stack("layers.{i}.self_attn_layer_norm.bias", lambda w: w),
            "wq": stack("layers.{i}.self_attn.q_proj.weight", lambda w: w.T.reshape(D, H, hd)),
            "bq": stack("layers.{i}.self_attn.q_proj.bias", lambda w: w.reshape(H, hd)),
            "wk": stack("layers.{i}.self_attn.k_proj.weight", lambda w: w.T.reshape(D, H, hd)),
            "bk": stack("layers.{i}.self_attn.k_proj.bias", lambda w: w.reshape(H, hd)),
            "wv": stack("layers.{i}.self_attn.v_proj.weight", lambda w: w.T.reshape(D, H, hd)),
            "bv": stack("layers.{i}.self_attn.v_proj.bias", lambda w: w.reshape(H, hd)),
            "wo": stack("layers.{i}.self_attn.out_proj.weight", lambda w: w.T.reshape(H, hd, D)),
            "bo": stack("layers.{i}.self_attn.out_proj.bias", lambda w: w),
            "ln2_scale": stack("layers.{i}.final_layer_norm.weight", lambda w: w),
            "ln2_bias": stack("layers.{i}.final_layer_norm.bias", lambda w: w),
            "fc1": stack("layers.{i}.fc1.weight", lambda w: w.T),
            "fc1_b": stack("layers.{i}.fc1.bias", lambda w: w),
            "fc2": stack("layers.{i}.fc2.weight", lambda w: w.T),
            "fc2_b": stack("layers.{i}.fc2.bias", lambda w: w),
        },
        "final_ln_scale": jnp.asarray(get("final_layer_norm.weight"), dtype),
        "final_ln_bias": jnp.asarray(get("final_layer_norm.bias"), dtype),
    }


def config_from_hf_falcon(hf_cfg: Any):
    from substratus_tpu.models.falcon import FalconConfig

    get = lambda n, d=None: getattr(hf_cfg, n, d)
    if not get("parallel_attn", True):
        raise NotImplementedError("non-parallel Falcon blocks not supported")
    if get("alibi", False):
        raise NotImplementedError("Falcon alibi positioning not supported")
    if get("bias", False):
        raise NotImplementedError("biased Falcon projections not supported")
    if not get("tie_word_embeddings", True):
        raise NotImplementedError(
            "untied Falcon LM heads not supported (forward scores against "
            "the tied token embedding)"
        )
    new_arch = bool(get("new_decoder_architecture", False))
    if new_arch:
        kv = get("num_kv_heads") or hf_cfg.num_attention_heads
    elif get("multi_query", True):
        kv = 1
    else:
        kv = hf_cfg.num_attention_heads
    return FalconConfig(
        vocab_size=hf_cfg.vocab_size,
        dim=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=kv,
        rope_theta=get("rope_theta", 10000.0),
        norm_eps=get("layer_norm_epsilon", 1e-5),
        max_seq_len=get("max_position_embeddings", 2048),
        separate_ln=new_arch,
    )


def convert_falcon_state_dict(sd: Mapping[str, Any], cfg, dtype=jnp.bfloat16) -> Params:
    """HF FalconForCausalLM state dict -> models/falcon.py params. The fused
    query_key_value weight interleaves per kv-group: (H/KH) query heads, one
    key head, one value head."""
    hd = cfg.head_size
    L, D, H, KH = cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads
    G = H // KH

    def get(name: str) -> np.ndarray:
        for prefix in ("transformer.", "model.transformer.", ""):
            if prefix + name in sd:
                return _np(sd[prefix + name])
        raise KeyError(name)

    def split_qkv(w: np.ndarray):
        # w: [(H + 2*KH)*hd, D] -> per-group [G q | k | v]
        grouped = w.reshape(KH, G + 2, hd, D)
        q = grouped[:, :G].reshape(H, hd, D).transpose(2, 0, 1)  # [D,H,hd]
        k = grouped[:, G].transpose(2, 0, 1)  # [D,KH,hd]
        v = grouped[:, G + 1].transpose(2, 0, 1)
        return q, k, v

    qs, ks, vs = [], [], []
    for i in range(L):
        q, k, v = split_qkv(get(f"h.{i}.self_attention.query_key_value.weight"))
        qs.append(q)
        ks.append(k)
        vs.append(v)

    def stack(fmt: str, transform) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([transform(get(fmt.format(i=i))) for i in range(L)]), dtype
        )

    ln1 = "h.{i}.ln_attn" if cfg.separate_ln else "h.{i}.input_layernorm"
    layers = {
        "ln1_scale": stack(ln1 + ".weight", lambda w: w),
        "ln1_bias": stack(ln1 + ".bias", lambda w: w),
        "wq": jnp.asarray(np.stack(qs), dtype),
        "wk": jnp.asarray(np.stack(ks), dtype),
        "wv": jnp.asarray(np.stack(vs), dtype),
        "wo": stack(
            "h.{i}.self_attention.dense.weight",
            lambda w: w.T.reshape(H, hd, D),
        ),
        "fc1": stack("h.{i}.mlp.dense_h_to_4h.weight", lambda w: w.T),
        "fc2": stack("h.{i}.mlp.dense_4h_to_h.weight", lambda w: w.T),
    }
    if cfg.separate_ln:
        layers["ln2_scale"] = stack("h.{i}.ln_mlp.weight", lambda w: w)
        layers["ln2_bias"] = stack("h.{i}.ln_mlp.bias", lambda w: w)
    return {
        "tok_embed": jnp.asarray(get("word_embeddings.weight"), dtype),
        "layers": layers,
        "final_ln_scale": jnp.asarray(get("ln_f.weight"), dtype),
        "final_ln_bias": jnp.asarray(get("ln_f.bias"), dtype),
    }


def _dispatch_hf(model_type: str):
    """transformers model_type -> (config_fn, convert_fn), via the family
    registry (models/registry.py is the single dispatch table)."""
    from substratus_tpu.models.registry import HF_MODEL_TYPES

    family = HF_MODEL_TYPES.get(model_type)
    if family == "opt":
        return config_from_hf_opt, convert_opt_state_dict
    if family == "llama":
        return config_from_hf, convert_llama_state_dict
    if family == "falcon":
        return config_from_hf_falcon, convert_falcon_state_dict
    raise NotImplementedError(
        f"unsupported HF model_type {model_type!r} "
        f"(supported: {sorted(HF_MODEL_TYPES)})"
    )


def load_pretrained(
    path_or_name: str, dtype=jnp.bfloat16
) -> Tuple[LlamaConfig, Params]:
    """Load an HF Llama-family checkpoint from a local dir (safetensors or
    torch bin via transformers)."""
    if os.path.isdir(path_or_name) and os.path.exists(
        os.path.join(path_or_name, "config.json")
    ):
        with open(os.path.join(path_or_name, "config.json")) as f:
            raw = json.load(f)
        from types import SimpleNamespace

        hf_ns = SimpleNamespace(**raw)
        cfg, convert = _dispatch_hf(raw.get("model_type", "llama"))
        cfg = cfg(hf_ns)
        sd: Dict[str, np.ndarray] = {}
        st_files = [
            f for f in os.listdir(path_or_name) if f.endswith(".safetensors")
        ]
        if st_files:
            # framework="torch" rather than "numpy": numpy has no bfloat16,
            # which is what Llama checkpoints ship in.
            from safetensors import safe_open

            for fname in sorted(st_files):
                with safe_open(
                    os.path.join(path_or_name, fname), framework="torch"
                ) as f:
                    for key in f.keys():
                        sd[key] = f.get_tensor(key)
        else:
            import torch

            for fname in sorted(os.listdir(path_or_name)):
                if fname.endswith(".bin"):
                    sd.update(
                        torch.load(
                            os.path.join(path_or_name, fname),
                            map_location="cpu",
                            weights_only=True,
                        )
                    )
        return cfg, convert(sd, cfg, dtype)

    # Fall back to transformers hub loading (requires network or cache).
    from transformers import AutoConfig, AutoModelForCausalLM

    hf_cfg = AutoConfig.from_pretrained(path_or_name)
    model = AutoModelForCausalLM.from_pretrained(path_or_name)
    cfg_fn, convert = _dispatch_hf(getattr(hf_cfg, "model_type", "llama"))
    cfg = cfg_fn(hf_cfg)
    return cfg, convert(model.state_dict(), cfg, dtype)
