"""Model-loader container entrypoint (container contract).

In-repo TPU-native replacement for `substratusai/model-loader-huggingface`
(SURVEY.md §2.2; examples/llama2-7b/base-model.yaml:7): imports a HuggingFace
checkpoint and writes a servable substratus artifact (Orbax params + config
sidecar + tokenizer files) to /content/artifacts.

    python -m substratus_tpu.load.main [--out /content/artifacts]

params.json keys: name (HF repo id or local path), config (named config for
weightless smoke imports), quantize (int8 stores quantized weights).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil

import jax


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/content/artifacts")
    ap.add_argument("--params", default="/content/params.json")
    ap.add_argument("--name", default=None)
    args = ap.parse_args(argv)

    from substratus_tpu.utils.jaxenv import honor_requested_platform

    honor_requested_platform()

    p = {}
    if os.path.exists(args.params):
        with open(args.params) as f:
            p = json.load(f)
    from substratus_tpu.utils.params import warn_unknown_keys

    warn_unknown_keys(
        p, ("name", "config", "quantize", "seed"), "load.main"
    )
    name = args.name or p.get("name")

    from substratus_tpu.models import llama
    from substratus_tpu.observability.propagation import context_from_env
    from substratus_tpu.observability.tracing import tracer
    from substratus_tpu.train.checkpoints import save_artifact

    # Joins the trace of whoever spawned this Job: the controller stamps
    # TRACEPARENT into the loader container (controller/workloads.py);
    # spans export next to the artifact so the import shows up in the
    # same trace as the reconcile that created the Job.
    with tracer.span(
        "load.run", parent=context_from_env(), source=name or "random"
    ):
        gguf_path = None
        if name:
            from substratus_tpu.load.gguf import (
                load_gguf, resolve_gguf_or_exit,
            )

            gguf_path = resolve_gguf_or_exit(name)
            if gguf_path is not None:
                # llama.cpp checkpoint file -> orbax artifact (same
                # importer serving and training use; load/gguf.py). Its
                # ValueErrors (non-llama arch, rope scaling) exit cleanly
                # like the resolver's.
                try:
                    cfg, params = load_gguf(gguf_path)
                except ValueError as e:
                    raise SystemExit(str(e))
            else:
                from substratus_tpu.load.hf import load_pretrained

                cfg, params = load_pretrained(name)
            meta = {"source": name}
        else:
            # Weightless smoke import (reference parallel: opt-125m CPU
            # smoke); config names resolve across every registered family.
            from substratus_tpu.models import registry

            cfg_name = p.get("config", "tiny")
            family, cfg = registry.find_named_config(cfg_name)
            params = family.init_params(
                cfg, jax.random.key(int(p.get("seed", 0)))
            )
            meta = {"source": f"random:{cfg_name}"}

        if p.get("quantize") == "int8":
            if isinstance(cfg, llama.LlamaConfig):
                from substratus_tpu.ops.quant import quantize_params

                params = jax.jit(
                    lambda x: quantize_params(x, llama.quant_contracting(cfg))
                )(params)
                meta["quantize"] = "int8"
            else:
                print(
                    "int8 quantization not supported for this family; "
                    "skipping"
                )

        save_artifact(args.out, params, cfg, extra_meta=meta)

        # Ship tokenizer artifacts alongside the weights so serving needs
        # no network access. A GGUF source carries its vocab in metadata:
        # export it as a metadata-only tokenizer.gguf sidecar
        # (load_tokenizer resolves it) — without this the converted
        # artifact would silently serve with the byte fallback.
        if gguf_path is not None:
            from substratus_tpu.load.gguf import (
                read_gguf, write_tokenizer_gguf,
            )

            src_meta, _ = read_gguf(gguf_path, with_tensors=False)
            if write_tokenizer_gguf(
                os.path.join(args.out, "tokenizer.gguf"), src_meta
            ):
                print("embedded tokenizer exported to tokenizer.gguf")
        if name and os.path.isdir(name):
            for fname in (
                "tokenizer.json", "tokenizer.model",
                "tokenizer_config.json", "special_tokens_map.json",
            ):
                src = os.path.join(name, fname)
                if os.path.exists(src):
                    shutil.copy(src, os.path.join(args.out, fname))
    try:
        tracer.export_jsonl(
            os.environ.get(
                "SUBSTRATUS_TRACE_EXPORT",
                os.path.join(args.out, "trace.jsonl"),
            )
        )
    except OSError as e:
        print(f"trace export failed (continuing): {e}", flush=True)
    print(f"model artifact written to {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
