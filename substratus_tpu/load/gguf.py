"""GGUF checkpoint import: bring a llama.cpp model file to TPU serving.

The reference's quantized serving path consumes GGUF through llama.cpp
(SURVEY.md §2.2 model-server-llama-cpp; reference
examples/llama2-13b-chat-gguf/base-model.yaml imports a 4-bit GGUF).
Here the same file loads straight into the TPU engine: the GGUF binary
is parsed (v2/v3), GGML-quantized tensors dequantize block-wise in
numpy, q/k projections un-permute from llama.cpp's rope layout back to
the HF convention our models use, and the result feeds the SAME
convert_llama_state_dict as an HF checkpoint. Serve with
`--quantize int4` to re-quantize into the TPU-native nibble-packed
layout (ops/quant4.py) — g128 grouping rather than GGML's 32-blocks,
because that is what the Pallas unpack-dequant matmul wants.

Format notes (GGUF spec, ggml/docs/gguf.md):
  header: magic "GGUF", version u32, n_tensors u64, n_kv u64
  kv: string key, u32 value-type, value (strings u64-length-prefixed;
      arrays are [elem-type u32][count u64][elems])
  tensor infos: name, n_dims u32, dims u64[n] (ne[0] = contiguous dim),
      ggml type u32, offset u64 (relative to the aligned data section)
  data: aligned to general.alignment (default 32)

Supported tensor types: F32, F16, Q4_0, Q4_1, Q5_0, Q8_0 — the llama.cpp
quantizations the reference's example images actually shipped.
"""
from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, List, Tuple

import numpy as np

GGUF_MAGIC = b"GGUF"

# ggml tensor types (type id -> (block elements, block bytes))
GGML_F32 = 0
GGML_F16 = 1
GGML_Q4_0 = 2
GGML_Q4_1 = 3
GGML_Q5_0 = 6
GGML_Q8_0 = 8
_BLOCK = {
    GGML_F32: (1, 4),
    GGML_F16: (1, 2),
    GGML_Q4_0: (32, 2 + 16),
    GGML_Q4_1: (32, 2 + 2 + 16),
    GGML_Q5_0: (32, 2 + 4 + 16),
    GGML_Q8_0: (32, 2 + 32),
}

# gguf metadata value types
_SCALAR_FMT = {
    0: "B", 1: "b", 2: "<H", 3: "<h", 4: "<I", 5: "<i", 6: "<f",
    7: "?", 10: "<Q", 11: "<q", 12: "<d",
}
_T_STRING = 8
_T_ARRAY = 9


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    return struct.unpack(fmt, f.read(size))[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8", "replace")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALAR_FMT:
        return _read(f, _SCALAR_FMT[vtype])
    if vtype == _T_STRING:
        return _read_string(f)
    if vtype == _T_ARRAY:
        etype = _read(f, "<I")
        count = _read(f, "<Q")
        return [_read_value(f, etype) for _ in range(count)]
    raise ValueError(f"gguf: unknown metadata value type {vtype}")


def _dequantize(raw: bytes, ggml_type: int, n: int) -> np.ndarray:
    """GGML block formats -> float32 [n]."""
    if ggml_type not in _BLOCK:
        raise ValueError(
            f"gguf: unsupported tensor type {ggml_type} (supported: "
            "F32/F16/Q4_0/Q4_1/Q5_0/Q8_0; K-quants like Q4_K are not — "
            "re-export the model with a supported quantization)"
        )
    if ggml_type == GGML_F32:
        return np.frombuffer(raw, "<f4", n).astype(np.float32)
    if ggml_type == GGML_F16:
        return np.frombuffer(raw, "<f2", n).astype(np.float32)
    qk, bsz = _BLOCK[ggml_type]
    nb = n // qk
    blocks = np.frombuffer(raw, np.uint8, nb * bsz).reshape(nb, bsz)
    if ggml_type == GGML_Q4_0:
        d = blocks[:, :2].copy().view("<f2").astype(np.float32)  # [nb, 1]
        qs = blocks[:, 2:]
        lo = (qs & 0x0F).astype(np.int8) - 8
        hi = (qs >> 4).astype(np.int8) - 8
        q = np.concatenate([lo, hi], axis=1)  # [nb, 32]: j, j+16 halves
        return (q * d).astype(np.float32).reshape(-1)
    if ggml_type == GGML_Q4_1:
        d = blocks[:, :2].copy().view("<f2").astype(np.float32)
        m = blocks[:, 2:4].copy().view("<f2").astype(np.float32)
        qs = blocks[:, 4:]
        lo = (qs & 0x0F).astype(np.float32)
        hi = (qs >> 4).astype(np.float32)
        q = np.concatenate([lo, hi], axis=1)
        return (q * d + m).astype(np.float32).reshape(-1)
    if ggml_type == GGML_Q5_0:
        d = blocks[:, :2].copy().view("<f2").astype(np.float32)
        qh = blocks[:, 2:6].copy().view("<u4")  # [nb, 1] fifth-bit mask
        qs = blocks[:, 6:]
        lo4 = (qs & 0x0F).astype(np.int32)
        hi4 = (qs >> 4).astype(np.int32)
        shifts = np.arange(32, dtype=np.uint32)
        bit = ((qh >> shifts) & 1).astype(np.int32)  # [nb, 32]
        lo = lo4 | (bit[:, :16] << 4)
        hi = hi4 | (bit[:, 16:] << 4)
        q = np.concatenate([lo, hi], axis=1) - 16
        return (q * d).astype(np.float32).reshape(-1)
    if ggml_type == GGML_Q8_0:
        d = blocks[:, :2].copy().view("<f2").astype(np.float32)
        q = blocks[:, 2:].copy().view(np.int8).astype(np.float32)
        return (q * d).astype(np.float32).reshape(-1)
    raise ValueError(f"gguf: unsupported tensor type {ggml_type}")


# (path, mtime) -> metadata dict. The serve startup parses the same file
# for weights and again for the tokenizer; vocab arrays are the bulk of
# the kv section and decode via per-element struct calls, so parse once.
_META_CACHE: Dict[Tuple[str, float], Dict[str, Any]] = {}


def read_gguf(
    path: str, with_tensors: bool = True
) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Parse a .gguf file -> (metadata dict, {tensor name: ndarray}).

    Tensor arrays come back in the llama.cpp/torch orientation
    ([out_features, in_features] for matmuls): GGUF dims are ne[0]=
    contiguous first, so the numpy shape is the reverse. F32 tensors stay
    f32 (exactness); everything else dequantizes to f16 — a 70B Q4 file
    would otherwise peak at ~8x its size in host RAM (the quantized
    source never had more than f16 precision anyway).

    with_tensors=False parses only the header/metadata (cheap: the
    tokenizer lives there; and cached per (path, mtime))."""
    import os as _os

    cache_key = (path, _os.path.getmtime(path))
    cached = _META_CACHE.get(cache_key)
    if cached is not None and not with_tensors:
        return cached, {}
    with open(path, "rb") as f:
        if f.read(4) != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file")
        version = _read(f, "<I")
        if version not in (2, 3):
            raise ValueError(f"{path}: unsupported GGUF version {version}")
        n_tensors = _read(f, "<Q")
        n_kv = _read(f, "<Q")
        meta: Dict[str, Any] = {}
        for _ in range(n_kv):
            key = _read_string(f)
            vtype = _read(f, "<I")
            meta[key] = _read_value(f, vtype)
        _META_CACHE.clear()  # one model per process; don't hoard vocabs
        _META_CACHE[cache_key] = meta
        if not with_tensors:
            return meta, {}
        infos: List[Tuple[str, Tuple[int, ...], int, int]] = []
        for _ in range(n_tensors):
            name = _read_string(f)
            n_dims = _read(f, "<I")
            ne = [_read(f, "<Q") for _ in range(n_dims)]
            ggml_type = _read(f, "<I")
            offset = _read(f, "<Q")
            infos.append((name, tuple(ne), ggml_type, offset))
        align = int(meta.get("general.alignment", 32))
        pos = f.tell()
        data_start = (pos + align - 1) // align * align
        tensors: Dict[str, np.ndarray] = {}
        for name, ne, ggml_type, offset in infos:
            if ggml_type not in _BLOCK:
                raise ValueError(
                    f"gguf: tensor {name!r} has unsupported type "
                    f"{ggml_type} (supported: F32/F16/Q4_0/Q4_1/Q5_0/"
                    "Q8_0; K-quants like Q4_K are not — re-export with a "
                    "supported quantization)"
                )
            n = 1
            for d in ne:
                n *= d
            qk, bsz = _BLOCK[ggml_type]
            nbytes = n // qk * bsz
            f.seek(data_start + offset)
            flat = _dequantize(f.read(nbytes), ggml_type, n)
            if ggml_type != GGML_F32:
                flat = flat.astype(np.float16)  # bound host-RAM peak
            # ne[0] is contiguous -> numpy shape is reversed(ne)
            tensors[name] = flat.reshape(tuple(reversed(ne)))
    return meta, tensors


def _unpermute_qk(w: np.ndarray, n_head: int) -> np.ndarray:
    """Invert llama.cpp's rope permutation on a q/k projection.

    llama.cpp's HF->GGUF conversion reorders each head's rows from HF's
    rotate-half layout [r0..r{h/2-1}, i0..i{h/2-1}] to interleaved pairs;
    our models (and convert_llama_state_dict) expect the HF layout, so
    invert it: rows were written as reshape(n_head, 2, h/2)->swap(1,2)."""
    out, dim = w.shape
    hd = out // n_head
    return (
        w.reshape(n_head, hd // 2, 2, dim)
        .swapaxes(1, 2)
        .reshape(out, dim)
    )


# gguf tensor name -> HF state-dict name ({i} = layer index)
_NAME_MAP = {
    "token_embd.weight": "embed_tokens.weight",
    "output_norm.weight": "norm.weight",
    "output.weight": "lm_head.weight",
    "blk.{i}.attn_norm.weight": "layers.{i}.input_layernorm.weight",
    "blk.{i}.attn_q.weight": "layers.{i}.self_attn.q_proj.weight",
    "blk.{i}.attn_k.weight": "layers.{i}.self_attn.k_proj.weight",
    "blk.{i}.attn_v.weight": "layers.{i}.self_attn.v_proj.weight",
    "blk.{i}.attn_output.weight": "layers.{i}.self_attn.o_proj.weight",
    "blk.{i}.ffn_norm.weight": "layers.{i}.post_attention_layernorm.weight",
    "blk.{i}.ffn_gate.weight": "layers.{i}.mlp.gate_proj.weight",
    "blk.{i}.ffn_up.weight": "layers.{i}.mlp.up_proj.weight",
    "blk.{i}.ffn_down.weight": "layers.{i}.mlp.down_proj.weight",
}


def load_gguf(path: str, dtype=None):
    """.gguf file -> (LlamaConfig, params pytree), ready for the engine.

    Only the llama architecture (which covers the Llama/Mistral GGUF
    ecosystem the reference example served); other architectures raise.
    """
    import jax.numpy as jnp

    from substratus_tpu.load.hf import convert_llama_state_dict
    from substratus_tpu.models.llama import LlamaConfig

    meta, tensors = read_gguf(path)
    arch = meta.get("general.architecture")
    if arch != "llama":
        raise ValueError(
            f"{path}: gguf architecture {arch!r} unsupported (llama only)"
        )
    p = "llama."
    scaling = meta.get(p + "rope.scaling.type")
    if scaling and scaling != "none":
        # loud-not-silent: serving to an extended context with unscaled
        # rope would produce garbage past the base window
        raise ValueError(
            f"{path}: rope scaling {scaling!r} is not supported — the "
            "model would misbehave beyond its base context"
        )
    n_heads = int(meta[p + "attention.head_count"])
    cfg = LlamaConfig(
        vocab_size=int(tensors["token_embd.weight"].shape[0]),
        dim=int(meta[p + "embedding_length"]),
        n_layers=int(meta[p + "block_count"]),
        n_heads=n_heads,
        n_kv_heads=int(meta.get(p + "attention.head_count_kv", n_heads)),
        head_dim=(
            int(meta[p + "attention.key_length"])
            if p + "attention.key_length" in meta else None
        ),
        hidden_dim=int(meta[p + "feed_forward_length"]),
        max_seq_len=int(meta.get(p + "context_length", 4096)),
        rope_theta=float(meta.get(p + "rope.freq_base", 10000.0)),
        norm_eps=float(
            meta.get(p + "attention.layer_norm_rms_epsilon", 1e-5)
        ),
        tie_embeddings="output.weight" not in tensors,
        dtype=dtype if dtype is not None else jnp.bfloat16,
    )

    sd: Dict[str, np.ndarray] = {}
    for gname, arr in tensors.items():
        parts = gname.split(".")
        if parts[0] == "blk":
            i = parts[1]
            key = ".".join(["blk", "{i}"] + parts[2:])
            hf = _NAME_MAP.get(key)
            if hf is None:
                continue  # rope freq tables etc. — derived, not loaded
            if parts[2] in ("attn_q", "attn_k"):
                heads = cfg.n_heads if parts[2] == "attn_q" else cfg.n_kv_heads
                arr = _unpermute_qk(arr, heads)
            sd[hf.format(i=i)] = arr
        else:
            hf = _NAME_MAP.get(gname)
            if hf is not None:
                sd[hf] = arr
    params = convert_llama_state_dict(sd, cfg, cfg.dtype)
    return cfg, params


class GGUFTokenizer:
    """SentencePiece-BPE tokenizer from the GGUF-embedded vocab
    (tokenizer.ggml.tokens/scores/token_type + bos/eos ids) — the same
    greedy highest-score bigram merge llama.cpp's SPM tokenizer runs, so
    a .gguf file serves standalone with its own real tokenizer.

    Token types follow the sentencepiece proto: 1 normal, 2 unknown,
    3 control (skipped on decode), 6 byte (`<0xXX>` pieces)."""

    def __init__(self, meta: Dict[str, Any]):
        t = "tokenizer.ggml."
        self.tokens: List[str] = meta[t + "tokens"]
        n = len(self.tokens)
        self.scores = meta.get(t + "scores") or [0.0] * n
        self.types = meta.get(t + "token_type") or [1] * n
        self.bos_id = int(meta.get(t + "bos_token_id", 1))
        self.eos_id = int(meta.get(t + "eos_token_id", 2))
        self.unk_id = int(meta.get(t + "unknown_token_id", 0))
        self.chat_template = meta.get("tokenizer.chat_template")
        self._compiled_template = None
        self._special_re = None
        self.vocab_size = n
        self._index = {tok: i for i, tok in enumerate(self.tokens)}
        self._byte = {}
        for i, (tok, ty) in enumerate(zip(self.tokens, self.types)):
            if ty == 6 and tok.startswith("<0x") and tok.endswith(">"):
                self._byte[int(tok[3:-1], 16)] = i
        self._native = None
        lib = _native_spm()
        if lib is not None:
            import ctypes

            toks = (ctypes.c_char_p * n)(
                *[t.encode("utf-8") for t in self.tokens]
            )
            scores = (ctypes.c_float * n)(*[float(s) for s in self.scores])
            byte_ids = (ctypes.c_int32 * 256)(
                *[self._byte.get(b, -1) for b in range(256)]
            )
            handle = lib.spm_create(toks, scores, n, byte_ids, self.unk_id)
            if handle:
                import weakref

                self._native = (lib, handle)
                # free the C++ vocab copy with the tokenizer object
                weakref.finalize(self, lib.spm_destroy, handle)

    def encode(self, text: str) -> List[int]:
        """BOS + greedy merge of the SP-normalized text (spaces->U+2581,
        one dummy prefix)."""
        return [self.bos_id] + self._encode_norm(
            "▁" + text.replace(" ", "▁")
        )

    def _encode_norm(self, norm: str) -> List[int]:
        """Greedy highest-score bigram merge (llama.cpp llm_tokenizer_spm)
        of an ALREADY-normalized piece string, no BOS, via a
        lazy-invalidated heap: O(n log n), safe on the request hot path
        for long prompts. Uses the C++ encoder when built (make spm;
        native/spm_tokenizer.cc — same algorithm, locked together by
        tests/test_spm_native.py)."""
        if self._native is not None:
            import ctypes

            raw = norm.encode("utf-8")
            lib, handle = self._native
            out = (ctypes.c_int32 * (len(raw) + 1))()
            count = lib.spm_encode(
                handle, raw, len(raw), out, len(raw) + 1
            )
            return list(out[:count])
        import heapq

        pieces = list(norm)
        n = len(pieces)
        prev = list(range(-1, n - 1))
        nxt = list(range(1, n + 1))
        alive = [True] * n

        def push(heap, i):
            j = nxt[i]
            if j >= n:
                return
            cand = pieces[i] + pieces[j]
            idx = self._index.get(cand)
            if idx is not None:
                # ties broken leftmost, like the linear scan
                heapq.heappush(heap, (-self.scores[idx], i, cand, idx))

        heap: List[Tuple[float, int, str, int]] = []
        for i in range(n - 1):
            push(heap, i)
        while heap:
            _, i, cand, idx = heapq.heappop(heap)
            j = nxt[i] if i < n else n
            # lazy invalidation: stale entries no longer describe the list
            if not (i < n and alive[i] and j < n and alive[j]
                    and pieces[i] + pieces[j] == cand):
                continue
            pieces[i] = cand
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[j] < n:
                prev[nxt[j]] = i
            if prev[i] >= 0:
                push(heap, prev[i])
            push(heap, i)
        out: List[int] = []
        i = 0
        while i < n:
            if not alive[i]:
                i += 1
                continue
            idx = self._index.get(pieces[i])
            if idx is not None:
                out.append(idx)
            else:
                for b in pieces[i].encode("utf-8"):  # byte fallback
                    out.append(self._byte.get(b, self.unk_id))
            i = nxt[i]
        return out

    def apply_chat_template(self, messages):
        """Render with the GGUF's embedded jinja chat template (the
        format the checkpoint was trained on; tokenizer.chat_template).
        Returns None when the file carries no template (callers fall back
        to the generic transcript)."""
        if not self.chat_template:
            return None
        if self._compiled_template is None:
            # Sandboxed: the template ships inside a downloaded model
            # file — same posture transformers takes. Compiled ONCE (this
            # runs per chat request); helpers transformers guarantees
            # (raise_exception, strftime_now, tojson) provided so real
            # Mistral/Zephyr/Llama-3 templates render.
            import datetime
            import json as _json

            from jinja2.sandbox import ImmutableSandboxedEnvironment

            env = ImmutableSandboxedEnvironment(
                keep_trailing_newline=True, autoescape=False,
            )

            def raise_exception(message):
                raise ValueError(f"chat template error: {message}")

            env.globals["raise_exception"] = raise_exception
            env.globals["strftime_now"] = (
                lambda fmt: datetime.datetime.now().strftime(fmt)
            )
            env.filters["tojson"] = lambda v, **kw: _json.dumps(v, **kw)
            self._compiled_template = env.from_string(self.chat_template)
        bos = self.tokens[self.bos_id] if self.bos_id < self.vocab_size else ""
        eos = self.tokens[self.eos_id] if self.eos_id < self.vocab_size else ""
        return self._compiled_template.render(
            messages=messages, add_generation_prompt=True,
            bos_token=bos, eos_token=eos,
        )

    def encode_templated(self, text: str) -> List[int]:
        """Encode a TEMPLATE-RENDERED prompt: control-token strings the
        template injected ('<s>', '<|im_start|>', ...) map to their ids
        instead of being SPM-merged as literal characters, and no BOS is
        auto-prepended beyond what the template itself rendered
        (llama.cpp's tokenize with parse_special=true)."""
        import re

        if self._special_re is None:
            specials = sorted(
                (t for t, ty in zip(self.tokens, self.types) if ty == 3),
                key=len, reverse=True,
            )
            self._special_re = re.compile(
                "(" + "|".join(map(re.escape, specials)) + ")"
            ) if specials else re.compile(r"(?!x)x")  # never matches
        out: List[int] = []
        first_segment = True
        for part in self._special_re.split(text):
            if not part:
                continue
            idx = self._index.get(part)
            if idx is not None and self.types[idx] == 3:
                out.append(idx)
                first_segment = False
                continue
            # SP-normalize the segment; the dummy ▁ prefix applies only
            # at the very start of raw text, never mid-template
            norm = part.replace(" ", "▁")
            if first_segment:
                norm = "▁" + norm
                first_segment = False
            out.extend(self._encode_norm(norm))
        return out

    def decode(self, ids: List[int]) -> str:
        buf = bytearray()
        for i in ids:
            if not 0 <= i < self.vocab_size or self.types[i] == 3:
                continue  # control tokens (bos/eos) don't render
            if self.types[i] == 6:
                buf += bytes([int(self.tokens[i][3:-1], 16)])
            else:
                buf += self.tokens[i].encode("utf-8")
        text = buf.decode("utf-8", "replace").replace("▁", " ")
        # strip exactly the ONE SentencePiece dummy-prefix space — more
        # would eat real leading whitespace (indented code continuations)
        return text[1:] if text.startswith(" ") else text


_SPM_LIB = "unloaded"


def _native_spm():
    """ctypes handle to the C++ SPM encoder (native/spm_tokenizer.cc),
    or None — pure Python stands in when the .so isn't built or
    SUBSTRATUS_SPM_NATIVE=0."""
    import ctypes
    import os

    # env toggle is NOT cached: tests (and operators) flip it at runtime
    if os.environ.get("SUBSTRATUS_SPM_NATIVE") == "0":
        return None
    global _SPM_LIB
    if _SPM_LIB != "unloaded":
        return _SPM_LIB
    _SPM_LIB = None
    so = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "native", "libspm_tokenizer.so",
    )
    if not os.path.exists(so):
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.spm_create.restype = ctypes.c_void_p
    lib.spm_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int32, ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.spm_encode.restype = ctypes.c_int32
    lib.spm_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
    ]
    lib.spm_destroy.restype = None
    lib.spm_destroy.argtypes = [ctypes.c_void_p]
    _SPM_LIB = lib
    return lib


def write_tokenizer_gguf(path: str, meta: Dict[str, Any]) -> bool:
    """Write a metadata-only .gguf holding a source file's tokenizer.* (+
    architecture) keys — the artifact-sidecar form of the embedded vocab,
    so a converted orbax artifact still serves with the model's real
    tokenizer (load_tokenizer resolves any *.gguf in the artifact dir,
    metadata-only). Returns False when the source had no tokenizer."""
    keep = {
        k: v for k, v in meta.items()
        if k.startswith("tokenizer.") or k == "general.architecture"
    }
    if "tokenizer.ggml.tokens" not in keep:
        return False

    def s(x: str) -> bytes:
        b = x.encode("utf-8")
        return struct.pack("<Q", len(b)) + b

    def value(v) -> bytes:
        if isinstance(v, bool):
            return struct.pack("<I", 7) + struct.pack("?", v)
        if isinstance(v, str):
            return struct.pack("<I", _T_STRING) + s(v)
        if isinstance(v, float):
            return struct.pack("<I", 6) + struct.pack("<f", v)
        if isinstance(v, int):
            return struct.pack("<I", 5) + struct.pack("<i", v)
        if isinstance(v, list):
            if all(isinstance(e, str) for e in v):
                etype, enc = _T_STRING, s
            elif all(isinstance(e, int) and not isinstance(e, bool)
                     for e in v):
                etype, enc = 5, lambda e: struct.pack("<i", e)
            else:
                etype, enc = 6, lambda e: struct.pack("<f", float(e))
            return (
                struct.pack("<I", _T_ARRAY) + struct.pack("<I", etype)
                + struct.pack("<Q", len(v))
                + b"".join(enc(e) for e in v)
            )
        raise ValueError(f"gguf: cannot serialize metadata value {v!r}")

    buf = bytearray()
    buf += GGUF_MAGIC + struct.pack("<I", 3)
    buf += struct.pack("<Q", 0) + struct.pack("<Q", len(keep))  # 0 tensors
    for k, v in keep.items():
        buf += s(k) + value(v)
    with open(path, "wb") as f:
        f.write(bytes(buf))
    return True


class UnsupportedGGUFTokenizer(ValueError):
    """The file embeds a vocab this importer can't drive (e.g. a BPE
    'gpt2' vocab — Llama-3-era GGUFs). Serving with a byte fallback would
    silently produce garbage, so callers must surface this."""


def tokenizer_from_gguf(path: str):
    """The embedded tokenizer of a .gguf file; None when the file carries
    no vocab at all (smoke files). Raises UnsupportedGGUFTokenizer for a
    vocab model we can't run — loud-not-silent, a mistokenized prompt is
    garbage out with no error anywhere else."""
    meta, _ = read_gguf(path, with_tensors=False)
    model = meta.get("tokenizer.ggml.model")
    if "tokenizer.ggml.tokens" not in meta and model is None:
        return None
    if model not in ("llama", "spm"):
        raise UnsupportedGGUFTokenizer(
            f"{path}: embedded tokenizer model {model!r} unsupported "
            "(SentencePiece only) — place a tokenizer.json next to the "
            "file to serve it"
        )
    if "tokenizer.ggml.tokens" not in meta:
        return None
    return GGUFTokenizer(meta)


def gguf_has_tensors(path: str) -> bool:
    """False only for a VALID gguf header declaring zero tensors — the
    metadata-only tokenizer sidecar write_tokenizer_gguf leaves inside
    converted artifacts. Unreadable/corrupt files return True so they
    still route to read_gguf, whose bad-magic error is the clearer one.
    Header: magic(4) version(4) tensor_count(8)."""
    try:
        with open(path, "rb") as f:
            head = f.read(16)
        if len(head) < 16 or head[:4] != GGUF_MAGIC:
            return True
        return struct.unpack("<Q", head[8:16])[0] > 0
    except OSError:
        return True


def resolve_gguf_or_exit(path: str):
    """resolve_gguf(strict=True) with the one-line SystemExit every
    entrypoint (load/train/serve) wants instead of a traceback."""
    try:
        return resolve_gguf(path, strict=True)
    except (FileNotFoundError, ValueError) as e:
        raise SystemExit(str(e))


def resolve_gguf(path: str, strict: bool = False, weights: bool = True):
    """The .gguf file behind a model path, or None for non-GGUF paths.

    strict=True raises on the ambiguous/missing cases (a path explicitly
    naming .gguf must exist; a dir with several .gguf files is a split
    checkpoint we don't support); strict=False returns None for them —
    the tokenizer resolver shares this so path semantics can't drift.

    weights=True (the checkpoint path) ignores metadata-only files when
    scanning a directory — a converted orbax artifact holds a
    tokenizer.gguf sidecar that must not shadow the orbax weights — and
    raises on an explicitly named metadata-only file. The tokenizer
    resolver passes weights=False: the sidecar is exactly what it wants."""
    import glob
    import os

    if path.endswith(".gguf"):
        if os.path.isfile(path):
            if weights and not gguf_has_tensors(path):
                if strict:
                    raise ValueError(
                        f"{path}: metadata-only GGUF (no tensors) — this is "
                        "a tokenizer sidecar, not a weight checkpoint"
                    )
                return None
            return path
        if strict:
            raise FileNotFoundError(f"no such file: {path}")
        return None
    if os.path.isdir(path):
        found = sorted(glob.glob(os.path.join(path, "*.gguf")))
        if weights:
            found = [f for f in found if gguf_has_tensors(f)]
        if len(found) > 1:
            if strict:
                raise ValueError(
                    f"{path}: {len(found)} .gguf files found — pass the "
                    "exact file (split/multi-shard GGUF is unsupported)"
                )
            return None
        if found:
            return found[0]
    return None
