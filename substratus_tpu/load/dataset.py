"""Dataset-loader container entrypoint (container contract).

In-repo replacement for `substratusai/dataset-loader-http` (SURVEY.md §2.2;
examples/datasets/*.yaml): fetches source files into /content/artifacts,
where a Model finetune later mounts them RO at /content/data.

    python -m substratus_tpu.load.dataset [--out /content/artifacts]

params.json keys: urls (list of http(s) sources), files (list of local
paths to copy — useful with pre-mounted volumes and in tests).
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import urllib.request


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/content/artifacts")
    ap.add_argument("--params", default="/content/params.json")
    args = ap.parse_args(argv)

    p = {}
    if os.path.exists(args.params):
        with open(args.params) as f:
            p = json.load(f)
    from substratus_tpu.utils.params import warn_unknown_keys

    warn_unknown_keys(p, ("urls", "files"), "load.dataset")
    os.makedirs(args.out, exist_ok=True)

    n = 0
    for url in p.get("urls", []):
        dest = os.path.join(args.out, os.path.basename(url.split("?")[0]))
        print(f"fetching {url} -> {dest}", flush=True)
        with urllib.request.urlopen(url, timeout=300) as r, open(
            dest, "wb"
        ) as f:
            shutil.copyfileobj(r, f)
        n += 1
    for path in p.get("files", []):
        dest = os.path.join(args.out, os.path.basename(path))
        shutil.copy(path, dest)
        n += 1
    if n == 0:
        print("warning: no sources given (params.urls / params.files empty)")
    print(f"dataset artifact written: {n} files in {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
