from substratus_tpu.load.hf import (
    config_from_hf,
    convert_llama_state_dict,
    load_pretrained,
)

__all__ = ["config_from_hf", "convert_llama_state_dict", "load_pretrained"]
