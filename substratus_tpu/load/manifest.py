"""Prompt-manifest I/O for offline batch generation (container contract).

A batch-generation run (serve/batchgen.py, docs/batch-generation.md) is
driven by a JSONL *manifest*: one JSON object per line, each describing
one generation request. The controller mounts it RO under /content/data
(the same Dataset-artifact mount a finetune uses for its corpus), and
the driver writes results as sharded JSONL under the run's artifact
directory. This module is the jax-free half of that contract — manifest
iteration, the completed-record scan that makes restarts exactly-once,
and shard naming — shared by the driver, the bench, and tests.

Manifest record keys (all but one of prompt/tokens optional):

    {"id": "doc-17",            # echoed into the output record
     "prompt": "Summarize: …",  # text — encoded with the run's tokenizer
     "tokens": [1, 2, 3],       # OR pre-tokenized ids (wins over prompt)
     "max_tokens": 64,          # per-record generation budget
     "temperature": 0.0, "top_p": 1.0,
     "model": "tenant-a"}       # LoRA adapter id (multi-tenant serving)

The record's *index* is its 0-based line number in the manifest — the
stable identity resume keys on: an output line carries its index, and a
restarted driver skips every index already present in a parseable
output line. A line torn by a mid-write kill fails to parse, is ignored
by the scan, and its record is simply generated again — into a NEW
shard (resumed runs never append to existing shards, so a torn tail can
never corrupt a fresh record).
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Iterator, List, Set, Tuple

SHARD_RE = re.compile(r"^shard-(\d{5})\.jsonl$")


def shard_name(idx: int) -> str:
    return f"shard-{idx:05d}.jsonl"


def record_prompt_tokens(rec: Dict[str, Any], tokenizer=None) -> List[int]:
    """The prompt token ids of one manifest record: explicit `tokens`
    win; otherwise `prompt` text through the run's tokenizer."""
    toks = rec.get("tokens")
    if toks is not None:
        if not isinstance(toks, list) or not all(
            isinstance(t, int) for t in toks
        ):
            raise ValueError(f"manifest 'tokens' must be a list of ints: {toks!r}")
        return list(toks)
    text = rec.get("prompt")
    if text is None:
        raise ValueError("manifest record needs 'prompt' or 'tokens'")
    if tokenizer is None:
        raise ValueError(
            "manifest record has text 'prompt' but the run has no tokenizer"
        )
    return tokenizer.encode(str(text))


def iter_manifest(path: str) -> Iterator[Tuple[int, Dict[str, Any]]]:
    """Yield (index, record) for every non-blank manifest line. The index
    is the line number (0-based, blanks included) so it never shifts when
    other lines change. A malformed line is a hard error naming it —
    silently skipping would violate exactly-once."""
    with open(path) as f:
        for lineno, line in enumerate(f):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(
                    f"{path}:{lineno + 1}: malformed manifest line ({e})"
                )
            if not isinstance(rec, dict):
                raise ValueError(
                    f"{path}:{lineno + 1}: manifest line is not an object"
                )
            yield lineno, rec


def count_records(path: str) -> int:
    n = 0
    with open(path) as f:
        for line in f:
            if line.strip():
                n += 1
    return n


def list_shards(out_dir: str) -> List[str]:
    if not os.path.isdir(out_dir):
        return []
    return sorted(
        os.path.join(out_dir, name)
        for name in os.listdir(out_dir)
        if SHARD_RE.match(name)
    )


def next_shard_index(out_dir: str) -> int:
    """First unused shard number. Resumed runs start a fresh shard past
    every existing one — appending after a torn tail line would glue new
    JSON onto the partial record and corrupt both."""
    last = -1
    for path in list_shards(out_dir):
        m = SHARD_RE.match(os.path.basename(path))
        last = max(last, int(m.group(1)))
    return last + 1


def completed_indices(out_dir: str) -> Set[int]:
    """Manifest indices already durably written across every shard.
    Unparseable lines (the torn tail of a killed run) and lines without
    an integer `index` are ignored — their records get regenerated."""
    done: Set[int] = set()
    for path in list_shards(out_dir):
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # torn tail of a killed run
                idx = rec.get("index") if isinstance(rec, dict) else None
                if isinstance(idx, int):
                    done.add(idx)
    return done


def write_manifest(path: str, records: List[Dict[str, Any]]) -> None:
    """Write a manifest (tests/bench helper; production manifests come
    from the Dataset artifact mount)."""
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
