"""LoRA adapters for the model families (llama, falcon, opt).

The reference's finetuning ran inside `substratusai/model-trainer-huggingface`
(SURVEY.md §2.2, examples/llama2-7b/finetuned-model.yaml) using HF PEFT-style
params; here LoRA is native: adapter pytrees parallel the stacked-layer base
params, the base stays frozen (and may be int8-quantized — QLoRA-style), and
only the adapters receive gradients, so FSDP only needs to all-gather the tiny
A/B matrices during the optimizer step.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


LoraParams = Dict[str, Any]

# Which projections get adapters (HF PEFT default for Llama is q,v).
DEFAULT_TARGETS = ("wq", "wv")


def init_lora(
    cfg,  # any family config with dim/n_heads/n_kv_heads/head_size/hidden_dim
    key: jax.Array,
    rank: int = 8,
    alpha: float = 16.0,
    targets: Tuple[str, ...] = DEFAULT_TARGETS,
    dtype=jnp.bfloat16,
) -> LoraParams:
    """A [L, in, r] is gaussian, B [L, r, ...out] is zero (standard LoRA init
    so training starts from the base model)."""
    hd = cfg.head_size
    out_shape = {
        "wq": (cfg.n_heads, hd),
        "wk": (cfg.n_kv_heads, hd),
        "wv": (cfg.n_kv_heads, hd),
        "wo": (cfg.dim,),
        "w_gate": (cfg.hidden_dim,),
        "w_up": (cfg.hidden_dim,),
        "w_down": (cfg.dim,),
    }
    in_dim = {
        "wq": cfg.dim, "wk": cfg.dim, "wv": cfg.dim,
        "wo": cfg.n_heads * hd,
        "w_gate": cfg.dim, "w_up": cfg.dim,
        "w_down": cfg.hidden_dim,
    }
    moe_mlp = (
        {"w_gate", "w_up", "w_down"}
        if getattr(cfg, "n_experts", 0) > 0
        else set()
    )
    keys = jax.random.split(key, len(targets))
    layers: Dict[str, Any] = {}
    for k, name in zip(keys, targets):
        if name in moe_mlp:
            # Expert-routed weights carry a leading expert dim: each expert
            # gets its own low-rank pair [L, E, in, r] x [L, E, r, out]
            # (applied inside the routed FFN, models/llama.py::_moe_ffn).
            E = cfg.n_experts
            a = (
                jax.random.normal(
                    k, (cfg.n_layers, E, in_dim[name], rank), jnp.float32
                ) * (1.0 / rank)
            ).astype(dtype)
            b = jnp.zeros((cfg.n_layers, E, rank) + out_shape[name], dtype)
        else:
            a = (
                jax.random.normal(
                    k, (cfg.n_layers, in_dim[name], rank), jnp.float32
                ) * (1.0 / rank)
            ).astype(dtype)
            b = jnp.zeros((cfg.n_layers, rank) + out_shape[name], dtype)
        layers[name] = {"a": a, "b": b}
    # NOTE: the adapter-layer tree alone is returned; the (static) scale
    # alpha/rank is NOT part of the pytree so it can never receive gradients
    # or weight decay. Callers pass {"layers": adapters, "scale": alpha/rank}
    # to models.llama.forward.
    return layers


def merge_lora(
    params: Any, adapters: LoraParams, scale: float
) -> Any:
    """Fold trained adapters into the base weights: W += scale * A @ B.

    Returns a dense params tree (quantized bases are dequantized first) ready
    for save_artifact/serving without adapter plumbing.
    """
    from substratus_tpu.ops.quant import QTensor, materialize

    out = dict(params)
    layers = dict(params["layers"])
    for name, ab in adapters.items():
        orig = layers[name]
        from substratus_tpu.ops.quant4 import Q4Tensor

        # Quantized bases (int8 QTensor, int4 Q4Tensor) merge into bf16 —
        # their own .dtype is the STORAGE dtype (int8/uint8) and casting
        # the merged float weights to it would destroy the model.
        out_dtype = (
            jnp.bfloat16 if isinstance(orig, (QTensor, Q4Tensor))
            else orig.dtype
        )
        w = materialize(orig, jnp.float32)
        eq = "ledr,ler...->led..." if ab["a"].ndim == 4 else "ldr,lr...->ld..."
        delta = jnp.einsum(
            eq,
            ab["a"].astype(jnp.float32),
            ab["b"].astype(jnp.float32),
        ) * scale
        if name == "wo":
            # adapter input is flattened [H*hd]; reshape delta to match W
            delta = delta.reshape(w.shape)
        layers[name] = (w + delta).astype(out_dtype)
    out["layers"] = layers
    return out


def lora_logical_axes(adapters: LoraParams) -> LoraParams:
    """Logical axes for the adapter-layer tree (rank never sharded)."""
    out_axes = {
        "wq": ("layers", "lora_rank", "heads", "head_dim"),
        "wk": ("layers", "lora_rank", "kv_heads", "head_dim"),
        "wv": ("layers", "lora_rank", "kv_heads", "head_dim"),
        "wo": ("layers", "lora_rank", "embed"),
        "w_gate": ("layers", "lora_rank", "mlp"),
        "w_up": ("layers", "lora_rank", "mlp"),
        "w_down": ("layers", "lora_rank", "embed"),
    }
    axes_layers = {}
    for name, ab in adapters.items():
        in_axis = "mlp" if name == "w_down" else "embed"
        if ab["a"].ndim == 4:  # expert-routed adapter (MoE mlp)
            out_axis = "embed" if name == "w_down" else "mlp"
            axes_layers[name] = {
                "a": ("layers", "expert", in_axis, "lora_rank"),
                "b": ("layers", "expert", "lora_rank", out_axis),
            }
        else:
            axes_layers[name] = {
                "a": ("layers", in_axis, "lora_rank"),
                "b": out_axes[name],
            }
    return axes_layers
