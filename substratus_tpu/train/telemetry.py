"""Structured train-loop telemetry: step histograms, throughput, MFU.

Replaces the train loop's bare `print(f"step {i} loss ...")` with a
`log_step` path that (1) observes step time and tokens/sec into the shared
registry — the exact signals the edge-accelerator characterization papers
compare on (PAPERS.md) — (2) derives an MFU gauge from tokens-per-step when
the chip's peak FLOPs are known, and (3) emits one machine-parseable JSON
line per logging interval, so log pipelines stop regex-scraping progress.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Optional

import jax

from substratus_tpu.observability.metrics import (
    METRICS,
    RATIO_BUCKETS,
    THROUGHPUT_BUCKETS,
)
from substratus_tpu.observability.tracing import tracer

log = logging.getLogger("substratus.train")

METRICS.histogram(
    "substratus_train_step_seconds",
    "Wall time of one optimizer step, device-synchronized (seconds).",
)
METRICS.histogram(
    "substratus_train_tokens_per_second",
    "Training throughput per step (global batch tokens / step seconds).",
    buckets=THROUGHPUT_BUCKETS,
)
METRICS.histogram(
    "substratus_train_mfu_ratio",
    "Model FLOPs utilization per step (6*N*tokens / peak), when the "
    "device's peak FLOPs are known.",
    buckets=RATIO_BUCKETS,
)
METRICS.histogram(
    "substratus_train_phase_seconds",
    "Wall time of one train-loop phase (seconds), labeled by phase: "
    "data_load (next batch from the dataset), step (the optimizer step, "
    "device-synchronized), checkpoint (checkpoint save, 0 when the step "
    "saved nothing).",
)
for _name, _help in (
    ("substratus_train_step", "Last completed optimizer step."),
    ("substratus_train_loss", "Loss at the last completed step."),
    ("substratus_train_mfu", "MFU at the last completed step (0 when the "
     "device's peak FLOPs are unknown)."),
):
    METRICS.describe(_name, _help, type="gauge")

# Per-chip dense peak FLOPs (bf16), for the MFU denominator. Unlisted
# device kinds (CPU test meshes included) report mfu=0 rather than a
# number computed against a made-up peak.
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops() -> Optional[float]:
    """Aggregate peak FLOPs of every addressable-or-not device in the run,
    or None when the device kind has no table entry."""
    devices = jax.devices()
    per_chip = PEAK_FLOPS.get(devices[0].device_kind)
    return per_chip * len(devices) if per_chip else None


class StepLogger:
    """Per-step telemetry sink for the train loop.

    `tokens_per_step` is the GLOBAL batch in tokens (batch_size * seq_len);
    `n_params` drives the standard 6*N*tokens FLOPs estimate (forward +
    backward for a dense decoder; attention FLOPs excluded, consistent
    with how MFU is quoted in the scaling literature)."""

    def __init__(
        self,
        n_params: int,
        tokens_per_step: int,
        peak_flops: Optional[float] = None,
        log_every: int = 10,
        emit=None,  # line sink, default print (flushes; container logs)
    ):
        self.n_params = int(n_params)
        self.tokens_per_step = int(tokens_per_step)
        self.peak_flops = peak_flops
        self.log_every = max(1, log_every)
        self._emit = emit or (lambda line: print(line, flush=True))
        self._t_start = time.perf_counter()

    def log_step(
        self, step: int, loss: float, step_seconds: float,
        last: bool = False,
        data_seconds: Optional[float] = None,
        checkpoint_seconds: Optional[float] = None,
    ) -> Optional[dict]:
        """Record one completed step. Histograms update every step; the
        JSON progress line is emitted every `log_every` steps (and on the
        final step). Returns the emitted record, or None.

        data_seconds / checkpoint_seconds are the step's phase splits
        (train/main.py times them around next(data) and maybe_save); when
        given they land in substratus_train_phase_seconds and on the JSON
        record, so a slow run triages to input pipeline vs device step vs
        checkpoint I/O from the artifact alone."""
        step_seconds = max(step_seconds, 1e-9)
        tps = self.tokens_per_step / step_seconds
        METRICS.observe("substratus_train_step_seconds", step_seconds)
        METRICS.observe("substratus_train_tokens_per_second", tps)
        METRICS.observe(
            "substratus_train_phase_seconds", step_seconds,
            {"phase": "step"},
        )
        if data_seconds is not None:
            METRICS.observe(
                "substratus_train_phase_seconds", data_seconds,
                {"phase": "data_load"},
            )
        if checkpoint_seconds is not None:
            METRICS.observe(
                "substratus_train_phase_seconds", checkpoint_seconds,
                {"phase": "checkpoint"},
            )
        mfu = 0.0
        if self.peak_flops:
            mfu = (6.0 * self.n_params * self.tokens_per_step) / (
                step_seconds * self.peak_flops
            )
            METRICS.observe("substratus_train_mfu_ratio", mfu)
        METRICS.set("substratus_train_step", step)
        METRICS.set("substratus_train_loss", float(loss))
        METRICS.set("substratus_train_mfu", mfu)
        if step % self.log_every and not last:
            return None
        record = {
            "event": "train_step",
            "step": step,
            "loss": round(float(loss), 6),
            "step_seconds": round(step_seconds, 4),
            "tokens_per_second": round(tps, 1),
            "mfu": round(mfu, 4),
            "elapsed_seconds": round(
                time.perf_counter() - self._t_start, 1
            ),
        }
        if data_seconds is not None:
            record["data_seconds"] = round(data_seconds, 4)
        if checkpoint_seconds is not None:
            record["checkpoint_seconds"] = round(checkpoint_seconds, 4)
        # Log/trace join: inside a span (train/main.py wraps the run in
        # `train.run`, itself parented from the spawning controller's
        # TRACEPARENT) every progress line names its trace — grep a slow
        # step's trace_id straight out of the container logs.
        ctx = tracer.current_context()
        if ctx is not None:
            record["trace_id"] = ctx.trace_id
            record["span_id"] = ctx.span_id
        self._emit(json.dumps(record, separators=(",", ":")))
        return record
