"""Training data pipeline: files -> packed fixed-shape token batches.

The reference's Dataset CR produced arbitrary files under /content/data via
external loader images (SURVEY.md §2.2, examples/datasets/*.yaml); the
trainer image consumed them opaquely. Here the consumption side is concrete
and TPU-shaped: documents are tokenized, joined with EOS, and packed into
dense [batch, seq_len] blocks — static shapes, no padding waste, so every
step feeds the MXU identically.

Supported inputs (a directory or a single file):
  *.jsonl  — {"text": ...} or {"prompt": ..., "completion": ...} per line
  *.txt    — plain text, one document per file
  *.npy    — pre-tokenized 1-D int array (concatenated token stream)
"""
from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional

import numpy as np


def _iter_documents(path: str) -> Iterator[str]:
    paths: List[str] = []
    if os.path.isdir(path):
        for root, _, files in os.walk(path):
            paths.extend(os.path.join(root, f) for f in sorted(files))
    else:
        paths = [path]
    for p in paths:
        if p.endswith(".jsonl"):
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    if "text" in row:
                        yield row["text"]
                    elif "prompt" in row:
                        yield str(row["prompt"]) + str(row.get("completion", ""))
        elif p.endswith(".txt"):
            with open(p) as f:
                yield f.read()


def _token_stream(path: str, tokenizer, eos_id: int) -> np.ndarray:
    """Tokenize every document once into one contiguous stream."""
    npys = []
    if os.path.isdir(path):
        for root, _, files in os.walk(path):
            npys.extend(
                os.path.join(root, f) for f in sorted(files) if f.endswith(".npy")
            )
    elif path.endswith(".npy"):
        npys = [path]
    chunks: List[np.ndarray] = []
    for p in npys:
        chunks.append(np.load(p).astype(np.int32).reshape(-1))
    for doc in _iter_documents(path):
        ids = tokenizer.encode(doc)
        chunks.append(np.asarray(ids + [eos_id], np.int32))
    if not chunks:
        raise FileNotFoundError(f"no training documents found under {path}")
    return np.concatenate(chunks)


class PackedDataset:
    """Infinite iterator of {"tokens": [B, S], "weights": [B, S]} batches."""

    def __init__(
        self,
        path: str,
        tokenizer,
        batch_size: int,
        seq_len: int,
        eos_id: Optional[int] = None,
        seed: int = 0,
    ):
        eos = eos_id if eos_id is not None else getattr(tokenizer, "eos_id", 0)
        stream = _token_stream(path, tokenizer, eos)
        n_blocks = len(stream) // seq_len
        if n_blocks == 0:
            # Tile tiny corpora up to one full block so smoke datasets work.
            reps = seq_len // max(1, len(stream)) + 1
            stream = np.tile(stream, reps)
            n_blocks = len(stream) // seq_len
        self.blocks = stream[: n_blocks * seq_len].reshape(n_blocks, seq_len)
        self.batch_size = batch_size
        self.rng = np.random.default_rng(seed)
        self.n_tokens = int(self.blocks.size)

    def __iter__(self):
        return self

    def __next__(self):
        idx = self.rng.integers(0, len(self.blocks), size=self.batch_size)
        tokens = self.blocks[idx]
        return {
            "tokens": tokens.astype(np.int32),
            "weights": np.ones_like(tokens, np.float32),
        }
