"""Training data pipeline: files -> packed fixed-shape token batches.

The reference's Dataset CR produced arbitrary files under /content/data via
external loader images (SURVEY.md §2.2, examples/datasets/*.yaml); the
trainer image consumed them opaquely. Here the consumption side is concrete
and TPU-shaped: documents are tokenized, joined with EOS, and packed into
dense [batch, seq_len] blocks — static shapes, no padding waste, so every
step feeds the MXU identically.

Supported inputs (a directory or a single file):
  *.jsonl  — {"text": ...} or {"prompt": ..., "completion": ...} per line
  *.txt    — plain text, one document per file
  *.npy    — pre-tokenized 1-D int array (concatenated token stream)
"""
from __future__ import annotations

import json
import os
from typing import Iterator, List, Optional

import numpy as np


def _iter_documents(path: str) -> Iterator[str]:
    paths: List[str] = []
    if os.path.isdir(path):
        # dirs.sort() pins the walk order: cross-host shard assignment
        # indexes sources by position, and readdir order differs between
        # hosts on network mounts.
        for root, dirs, files in os.walk(path):
            dirs.sort()
            paths.extend(os.path.join(root, f) for f in sorted(files))
    else:
        paths = [path]
    for p in paths:
        if p.endswith(".jsonl"):
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    if "text" in row:
                        yield row["text"]
                    elif "prompt" in row:
                        yield str(row["prompt"]) + str(row.get("completion", ""))
        elif p.endswith(".txt"):
            with open(p) as f:
                yield f.read()


def _token_stream(
    path: str, tokenizer, eos_id: int, shard: int = 0, num_shards: int = 1
) -> np.ndarray:
    """Tokenize this shard's documents once into one contiguous stream.

    Sources (pre-tokenized .npy chunks first, then text documents) are
    assigned round-robin by a single global index, so with num_shards =
    jax.process_count() each host tokenizes and holds only ~1/N of the
    corpus — no whole-corpus materialization per worker (round-4 VERDICT
    weak #5). A shard that would come up empty (fewer sources than
    shards: smoke corpora) falls back to the full corpus rather than
    crashing; duplicated blocks across hosts only skew sampling, never
    correctness."""

    def build(own_all: bool) -> List[np.ndarray]:
        npys = []
        if os.path.isdir(path):
            # Same deterministic-walk requirement as _iter_documents:
            # every host must enumerate sources in the identical order
            # or round-robin ownership desyncs (dropped/duplicated
            # sources).
            for root, dirs, files in os.walk(path):
                dirs.sort()
                npys.extend(
                    os.path.join(root, f)
                    for f in sorted(files)
                    if f.endswith(".npy")
                )
        elif path.endswith(".npy"):
            npys = [path]
        chunks: List[np.ndarray] = []
        src = 0
        for p in npys:
            if own_all or src % num_shards == shard:
                chunks.append(np.load(p).astype(np.int32).reshape(-1))
            src += 1
        for doc in _iter_documents(path):
            if own_all or src % num_shards == shard:
                ids = tokenizer.encode(doc)
                chunks.append(np.asarray(ids + [eos_id], np.int32))
            src += 1
        if not chunks and src == 0:
            raise FileNotFoundError(
                f"no training documents found under {path}"
            )
        return chunks

    chunks = build(own_all=num_shards <= 1)
    if not chunks:
        chunks = build(own_all=True)
    return np.concatenate(chunks)


class PackedDataset:
    """Infinite iterator of {"tokens": [B, S], "weights": [B, S]} batches.

    Multi-host: pass shard=jax.process_index(), num_shards=
    jax.process_count() and a PER-PROCESS batch_size (global/N); each
    host tokenizes only its source shard and draws from its own blocks
    with a shard-decorrelated RNG. The trainer assembles the global
    batch from the per-process slices
    (make_array_from_process_local_data, train/trainer.py) — no
    identical-RNG coupling between hosts.

    shuffle=False iterates blocks sequentially (cycling) — deterministic
    order for parity tests and eval passes."""

    def __init__(
        self,
        path: str,
        tokenizer,
        batch_size: int,
        seq_len: int,
        eos_id: Optional[int] = None,
        seed: int = 0,
        shard: int = 0,
        num_shards: int = 1,
        shuffle: bool = True,
    ):
        eos = eos_id if eos_id is not None else getattr(tokenizer, "eos_id", 0)
        stream = _token_stream(path, tokenizer, eos, shard, num_shards)
        n_blocks = len(stream) // seq_len
        if n_blocks == 0:
            # Tile tiny corpora up to one full block so smoke datasets work.
            reps = seq_len // max(1, len(stream)) + 1
            stream = np.tile(stream, reps)
            n_blocks = len(stream) // seq_len
        self.blocks = stream[: n_blocks * seq_len].reshape(n_blocks, seq_len)
        self.batch_size = batch_size
        self.rng = np.random.default_rng(seed + shard)
        self.n_tokens = int(self.blocks.size)
        self.shuffle = shuffle
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self.shuffle:
            idx = self.rng.integers(0, len(self.blocks), size=self.batch_size)
        else:
            idx = (self._pos + np.arange(self.batch_size)) % len(self.blocks)
            self._pos = int((self._pos + self.batch_size) % len(self.blocks))
        tokens = self.blocks[idx]
        return {
            "tokens": tokens.astype(np.int32),
            "weights": np.ones_like(tokens, np.float32),
        }
