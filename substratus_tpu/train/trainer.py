"""pjit trainer: FSDP/TP/SP-sharded training with optional LoRA.

This is the in-repo replacement for the reference's external
`substratusai/model-trainer-huggingface` image (SURVEY.md §2.2). Where that
image ran single-pod HF Trainer on CUDA (max seen: 8xL4 on one node,
examples/falcon-40b/finetuned-model.yaml), this trainer is written for SPMD
over a TPU mesh from the start:

  * one jitted train step with NamedSharding-annotated params/opt-state;
    XLA inserts the all-gathers/reduce-scatters FSDP needs;
  * optional LoRA mode: base params frozen (optionally int8), gradients and
    optimizer state only for adapters;
  * remat (jax.checkpoint) over each scanned block to trade FLOPs for HBM;
  * loss masking via a per-token weight array (padding / prompt masking).

Container contract: `python -m substratus_tpu.train.main` reads
/content/params.json, data from /content/data, base model from
/content/model, writes checkpoints to /content/artifacts (reference:
docs/container-contract.md:5-56).
"""
from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from substratus_tpu.models import llama
from substratus_tpu.models.llama import LlamaConfig, Params
from substratus_tpu.parallel.sharding import (
    DEFAULT_RULES,
    LogicalRules,
    shard_tree,
    sharding_tree,
)
from substratus_tpu.train import lora as lora_lib
from substratus_tpu.utils.jaxcompat import ambient_mesh


@dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 2e-5
    weight_decay: float = 0.0
    warmup_steps: int = 10
    total_steps: int = 100
    grad_clip: float = 1.0
    b1: float = 0.9
    b2: float = 0.999
    # LoRA: rank 0 disables (full finetune)
    lora_rank: int = 0
    lora_alpha: float = 16.0
    # Projections to adapt (train/lora.py); on MoE models the mlp names
    # (w_gate/w_up/w_down) select expert-routed adapters.
    lora_targets: tuple = ("wq", "wv")
    remat: bool = True
    seed: int = 0
    # Gradient accumulation: the global batch splits into this many
    # microbatches scanned inside the jitted step (activation memory scales
    # with the microbatch, optimizer cadence with the global batch).
    grad_accum_steps: int = 1


def cross_entropy_sum(
    logits: jnp.ndarray,  # [B, S, V] float32
    targets: jnp.ndarray,  # [B, S] int32
    weights: Optional[jnp.ndarray] = None,  # [B, S] 0/1 loss mask
) -> tuple:
    """(weighted nll sum, weight sum) — the accumulation-friendly form."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if weights is None:
        weights = jnp.ones_like(nll)
    weights = weights.astype(jnp.float32)
    return (nll * weights).sum(), weights.sum()


def cross_entropy_loss(
    logits: jnp.ndarray,
    targets: jnp.ndarray,
    weights: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    s, w = cross_entropy_sum(logits, targets, weights)
    return s / jnp.maximum(w, 1.0)


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=tc.learning_rate,
        warmup_steps=tc.warmup_steps,
        decay_steps=max(tc.total_steps, tc.warmup_steps + 1),
    )
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(
            schedule, b1=tc.b1, b2=tc.b2, weight_decay=tc.weight_decay
        ),
    )


class Trainer:
    """Owns sharded params/opt-state and the jitted train step.

    In LoRA mode `trainable` is the adapter tree and `params` stays frozen;
    otherwise `trainable` IS the params tree.
    """

    def __init__(
        self,
        cfg: LlamaConfig,
        tc: TrainConfig,
        mesh: Mesh,
        params: Optional[Params] = None,
        rules: LogicalRules = DEFAULT_RULES,
        model=None,
    ):
        """model: the model-family module; resolved from the config type via
        models/registry.py when omitted, so any registered family trains."""
        from substratus_tpu.models import registry

        self.model = model if model is not None else registry.module_of(cfg)
        self.cfg, self.tc, self.mesh, self.rules = cfg, tc, mesh, rules
        self.optimizer = make_optimizer(tc)
        key_params, key_lora = jax.random.split(jax.random.key(tc.seed))

        # sharding_tree (not logical_sharding): it sees the shapes, so
        # non-divisible dims (e.g. MQA's single kv head vs a tensor axis)
        # fall back to replication instead of erroring.
        param_shapes = jax.eval_shape(
            partial(self.model.init_params, cfg), jax.random.key(0)
        )
        param_sh = sharding_tree(
            param_shapes, mesh, self.model.param_logical_axes(cfg), rules
        )
        if params is None:
            init = jax.jit(
                partial(self.model.init_params, cfg), out_shardings=param_sh
            )
            params = init(key_params)
        else:
            # shard_tree handles both dense and int8-QTensor (QLoRA) bases.
            params = shard_tree(
                params, mesh, self.model.param_logical_axes(cfg), rules
            )
        self.params = params
        self.param_shardings = param_sh

        if tc.lora_rank > 0 and not getattr(self.model, "SUPPORTS_LORA", False):
            raise NotImplementedError(
                f"LoRA is not implemented for the "
                f"{self.model.__name__.split('.')[-1]} family; use full "
                "finetuning (lora_rank: 0)"
            )
        if tc.lora_rank > 0:
            adapters = lora_lib.init_lora(
                cfg, key_lora, rank=tc.lora_rank, alpha=tc.lora_alpha,
                targets=tuple(tc.lora_targets),
            )
            self.lora_scale = tc.lora_alpha / tc.lora_rank
            # Shape-aware (like params): MQA kv adapters replicate rather
            # than error when kv_heads doesn't divide the tensor axis.
            self.lora_shardings = sharding_tree(
                adapters, mesh, lora_lib.lora_logical_axes(adapters), rules
            )
            self.lora = jax.tree.map(
                jax.device_put, adapters, self.lora_shardings
            )
            trainable_sh = self.lora_shardings
            trainable = self.lora
        else:
            self.lora = None
            self.lora_scale = None
            self.lora_shardings = None
            trainable_sh = param_sh
            trainable = params

        self.opt_state = jax.jit(
            self.optimizer.init,
            out_shardings=self._opt_shardings(trainable_sh),
        )(trainable)
        self.step = 0

        batch_spec = rules.mesh_axes(("batch", "seq"))
        self.batch_sharding = NamedSharding(mesh, batch_spec)
        self._train_step = self._build_train_step()

    def _opt_shardings(self, trainable_sh):
        """Optimizer-state shardings: moment buffers mirror their param's
        sharding (matched structurally via optax's param-tree mapping),
        scalars (step counts) replicate."""
        import optax.tree_utils as otu

        trainable_shapes = self._trainable_shapes(trainable_sh)
        opt_shapes = jax.eval_shape(self.optimizer.init, trainable_shapes)
        replicated = NamedSharding(self.mesh, P())
        return otu.tree_map_params(
            self.optimizer,
            lambda _, sh: sh,
            opt_shapes,
            trainable_sh,
            transform_non_params=lambda _: replicated,
        )

    def _trainable_shapes(self, trainable_sh):
        src = self.lora if self.lora is not None else self.params
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), src
        )

    def _build_train_step(self):
        cfg, tc = self.cfg, self.tc
        optimizer = self.optimizer
        lora_mode = tc.lora_rank > 0

        lora_scale = self.lora_scale if lora_mode else None

        def loss_fn(trainable, frozen_params, batch):
            if lora_mode:
                params = frozen_params
                lora = {"layers": trainable, "scale": lora_scale}
            else:
                params, lora = trainable, None
            logits, kv = self.model.forward(
                params,
                batch["tokens"],
                cfg,
                lora=lora,
                remat=tc.remat,
                train=True,
            )
            loss = cross_entropy_loss(
                logits[:, :-1], batch["tokens"][:, 1:], batch["weights"][:, 1:]
            )
            if "moe_aux" in kv:  # router load balancing (MoE models)
                loss = loss + cfg.router_aux_weight * kv["moe_aux"].mean()
            return loss

        accum = max(1, tc.grad_accum_steps)

        def sum_loss_fn(trainable, frozen_params, mb):
            """(weighted-nll sum [+ token-weighted moe aux], weight sum) —
            summing (not averaging) per microbatch makes accumulation
            exactly equal to the single-step update even when loss-mask
            token counts differ across microbatches."""
            if lora_mode:
                params = frozen_params
                lora = {"layers": trainable, "scale": lora_scale}
            else:
                params, lora = trainable, None
            logits, kv = self.model.forward(
                params, mb["tokens"], cfg, lora=lora, remat=tc.remat,
                train=True,
            )
            s, w = cross_entropy_sum(
                logits[:, :-1], mb["tokens"][:, 1:], mb["weights"][:, 1:]
            )
            if "moe_aux" in kv:
                s = s + cfg.router_aux_weight * kv["moe_aux"].mean() * w
            return s, w

        def train_step(trainable, frozen_params, opt_state, batch):
            if accum == 1:
                loss, grads = jax.value_and_grad(loss_fn)(
                    trainable, frozen_params, batch
                )
            else:
                # Scan microbatches, accumulating grad-of-sum in f32; one
                # optimizer update per global batch, normalized once by the
                # total token weight.
                micro = jax.tree.map(
                    lambda x: x.reshape(
                        (accum, x.shape[0] // accum) + x.shape[1:]
                    ),
                    batch,
                )

                def acc_step(carry, mb):
                    s_sum, w_sum, grads = carry
                    (s, w), g = jax.value_and_grad(
                        sum_loss_fn, has_aux=True
                    )(trainable, frozen_params, mb)
                    grads = jax.tree.map(
                        lambda a, b: a + b.astype(jnp.float32), grads, g
                    )
                    return (s_sum + s, w_sum + w, grads), None

                zero = jax.tree.map(
                    lambda x: jnp.zeros(x.shape, jnp.float32), trainable
                )
                (s_sum, w_sum, grads), _ = jax.lax.scan(
                    acc_step,
                    (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), zero),
                    micro,
                )
                denom = jnp.maximum(w_sum, 1.0)
                loss = s_sum / denom
                # Cast back to param dtype so optimizer-state dtypes match
                # the non-accumulated path (donation needs stable types).
                grads = jax.tree.map(
                    lambda g, p: (g / denom).astype(p.dtype), grads, trainable
                )
            updates, opt_state = optimizer.update(
                grads, opt_state, trainable
            )
            trainable = optax.apply_updates(trainable, updates)
            return trainable, opt_state, loss

        donate = (0, 2)  # trainable + opt_state buffers
        return jax.jit(train_step, donate_argnums=donate)

    def train_step(
        self, batch: Dict[str, jnp.ndarray], batch_is_global: bool = False
    ) -> float:
        """batch: {"tokens": [B, S] int32, "weights": [B, S] 0/1}.

        Multi-process: B is the PER-PROCESS slice (global/N); the global
        batch assembles from every process's local rows via
        make_array_from_process_local_data, so no host ever materializes
        (or needs to agree on) the whole batch.

        batch_is_global: every process passed the IDENTICAL full global
        batch (train/main.py falls back to this when dp_total doesn't
        divide across processes) — placement then slices each process's
        addressable rows out of the full array instead of concatenating
        per-process shards."""
        nproc = jax.process_count()
        b = batch["tokens"].shape[0] * (1 if batch_is_global else nproc)
        dp = self.mesh.shape["data"] * self.mesh.shape["fsdp"]
        if b % dp:
            raise ValueError(
                f"global batch size {b} must be divisible by data*fsdp={dp} "
                f"(mesh {dict(self.mesh.shape)})"
            )
        accum = max(1, self.tc.grad_accum_steps)
        if b % accum or (b // accum) % dp:
            raise ValueError(
                f"global batch size {b} must split into "
                f"grad_accum_steps={accum} microbatches each divisible by "
                f"data*fsdp={dp}"
            )
        if nproc > 1:
            batch = jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(
                    self.batch_sharding, np.asarray(x),  # sublint: allow[hostsync]: incoming batch is host data; numpy is what every process can feed identically
                    global_shape=(
                        np.asarray(x).shape if batch_is_global else None  # sublint: allow[hostsync]: same host-side batch, shape probe only
                    ),
                ),
                batch,
            )
        else:
            batch = jax.tree.map(
                lambda x: jax.device_put(x, self.batch_sharding), batch
            )
        trainable = self.lora if self.lora is not None else self.params
        # Ambient mesh: the ring-attention path (cfg.attn_impl == "ring")
        # opens a shard_map over the "sequence" axis inside the jitted step.
        with ambient_mesh(self.mesh):
            trainable, self.opt_state, loss = self._train_step(
                trainable, self.params if self.lora is not None else None,
                self.opt_state, batch,
            )
        if self.lora is not None:
            self.lora = trainable
        else:
            self.params = trainable
        self.step += 1
        return float(loss)

    def snapshot_params(self) -> Params:
        """A host-resident COPY of the live param tree, safe to hand to
        a consumer that outlives the next train_step. The jitted step
        donates the trainable buffers (donate_argnums=(0, 2)), so
        `self.params` leaves are invalidated and rewritten every step —
        handing the live tree to `Engine.swap_params` would alias
        buffers the next step clobbers. The copy is device_get, not
        jnp.array: a device copy would keep the trainer's mesh sharding,
        and installing mesh-sharded leaves into a single-device engine
        turns its decode step into a multi-device collective program
        (which deadlocks against the trainer's own collectives when both
        run in one process). Host numpy is the placement-neutral
        interchange — each engine re-places it for its own topology on
        install. The RL actor-learner loop (rl/loop.py) ships weights
        to actors exclusively through this."""
        return jax.device_get(self.params)
