"""Training container entrypoint (container contract).

In-repo TPU-native replacement for `substratusai/model-trainer-huggingface`
(SURVEY.md §2.2; examples/llama2-7b/finetuned-model.yaml). Contract
(docs/container-contract.md:5-36): base model RO at /content/model, dataset
RO at /content/data, hyperparameters at /content/params.json, outputs to
/content/artifacts.

    python -m substratus_tpu.train.main [--data DIR] [--model DIR] [--out DIR]

params.json keys (HF-trainer-style names kept where the reference examples
used them): steps, batch_size, seq_len, learning_rate, save_steps,
lora_rank, lora_alpha, quantize (int8 => QLoRA), config (named model config
when training from scratch), dp/fsdp/tensor/sequence (mesh axis sizes,
default: all devices on fsdp).
"""
from __future__ import annotations

import argparse
import json
import os
import time

import jax
import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default="/content/data")
    ap.add_argument("--model", default=None, help="base model dir (optional)")
    ap.add_argument("--out", default="/content/artifacts")
    ap.add_argument("--params", default="/content/params.json")
    args = ap.parse_args(argv)

    from substratus_tpu.utils.jaxenv import honor_requested_platform

    honor_requested_platform()

    p = {}
    if os.path.exists(args.params):
        with open(args.params) as f:
            p = json.load(f)

    from substratus_tpu.utils.params import warn_unknown_keys

    warn_unknown_keys(
        p,
        (
            "steps", "max_steps", "batch_size", "seq_len", "learning_rate",
            "warmup_steps", "save_steps", "lora_rank", "lora_alpha",
            "quantize", "config", "dp", "fsdp", "sequence", "tensor",
            "remat", "seed", "grad_accum_steps", "profile_steps",
            "attn_impl",
        ),
        "train.main",
    )

    from substratus_tpu.models import llama
    from substratus_tpu.parallel.mesh import build_mesh
    from substratus_tpu.serve.tokenizer import load_tokenizer
    from substratus_tpu.train.checkpoints import (
        CheckpointManager,
        maybe_restore_orbax,
        save_artifact,
    )
    from substratus_tpu.train.data import PackedDataset
    from substratus_tpu.train.lora import merge_lora
    from substratus_tpu.train.telemetry import StepLogger, device_peak_flops
    from substratus_tpu.train.trainer import TrainConfig, Trainer

    steps = int(p.get("steps", p.get("max_steps", 100)))
    batch_size = int(p.get("batch_size", 8))
    seq_len = int(p.get("seq_len", 512))
    lora_rank = int(p.get("lora_rank", 0))

    model_dir = args.model or (
        "/content/model" if os.path.isdir("/content/model") else None
    )
    params = None
    if model_dir:
        from substratus_tpu.load.gguf import resolve_gguf_or_exit

        gguf_path = resolve_gguf_or_exit(model_dir)
        if gguf_path is not None:
            # fine-tune straight off a llama.cpp checkpoint (same importer
            # serving uses; weights dequantize to the training dtype)
            from substratus_tpu.load.gguf import load_gguf

            cfg, params = load_gguf(gguf_path)
        else:
            restored = maybe_restore_orbax(model_dir)
            if restored is not None:
                cfg, params = restored
            else:
                from substratus_tpu.load.hf import load_pretrained

                cfg, params = load_pretrained(model_dir)
        tokenizer = load_tokenizer(model_dir)
    else:
        from substratus_tpu.models import registry

        _, cfg = registry.find_named_config(p.get("config", "tiny"))
        tokenizer = load_tokenizer(None)
        if cfg.vocab_size < tokenizer.vocab_size:
            cfg = cfg.replace(vocab_size=tokenizer.vocab_size)

    if p.get("quantize") == "int8" and params is not None:
        from substratus_tpu.ops.quant import is_quantized, quantize_params

        if not is_quantized(params):  # int8 artifacts arrive pre-quantized
            params = jax.jit(
                lambda x: quantize_params(x, llama.quant_contracting(cfg))
            )(params)

    n_dev = len(jax.devices())
    mesh = build_mesh(
        data=int(p.get("dp", 1)),
        fsdp=int(p.get("fsdp", -1)),
        sequence=int(p.get("sequence", 1)),
        tensor=int(p.get("tensor", 1)),
    )
    dp_total = mesh.shape["data"] * mesh.shape["fsdp"]
    accum = max(1, int(p.get("grad_accum_steps", 1)))
    nproc = jax.process_count()
    # Each of the `accum` microbatches must itself split over data*fsdp,
    # and the global batch must slice evenly across processes (each host
    # loads only its own rows; train/data.py shard args below).
    unit = dp_total * accum
    if unit % nproc:
        import math

        unit = unit * nproc // math.gcd(unit, nproc)
    if batch_size % unit:
        batch_size = ((batch_size // unit) + 1) * unit
        print(
            f"batch_size rounded up to {batch_size} (multiple of "
            f"{unit} = lcm(data*fsdp*grad_accum_steps, processes))",
            flush=True,
        )
    # Context parallelism: {"sequence": N, "attn_impl": "ring"|"ulysses"}
    # shards attention over the sequence axis (llama family).
    attn_impl = p.get("attn_impl")
    if attn_impl is not None:
        if attn_impl not in ("xla", "flash", "ring", "ulysses"):
            raise SystemExit(f"unknown attn_impl {attn_impl!r}")
        if hasattr(cfg, "attn_impl"):
            cfg = cfg.replace(attn_impl=attn_impl)
        else:
            print(f"attn_impl ignored for the {type(cfg).__name__} family")

    tc = TrainConfig(
        learning_rate=float(p.get("learning_rate", 2e-5)),
        warmup_steps=int(p.get("warmup_steps", min(10, steps // 10 + 1))),
        total_steps=steps,
        lora_rank=lora_rank,
        lora_alpha=float(p.get("lora_alpha", 16.0)),
        remat=bool(p.get("remat", True)),
        seed=int(p.get("seed", 0)),
        grad_accum_steps=int(p.get("grad_accum_steps", 1)),
    )
    trainer = Trainer(cfg, tc, mesh, params=params)
    # Per-process dataset sharding is only sound when the global batch dim
    # actually shards across processes: data/fsdp are the LEADING mesh
    # axes (parallel/mesh.py), so each process owns a contiguous block of
    # batch rows exactly when data*fsdp is a multiple of the process
    # count. Otherwise (e.g. a tensor-only multi-host mesh, dp_total=1,
    # nproc=2) the batch dim is replicated-or-uneven across hosts and
    # per-process shards would SILENTLY diverge — every replica must see
    # identical values, so fall back to every host loading the identical
    # full batch instead.
    shard_data = nproc > 1 and dp_total % nproc == 0
    if nproc > 1 and not shard_data:
        print(
            f"per-process dataset sharding disabled: data*fsdp={dp_total} "
            f"does not divide across {nproc} processes; every host loads "
            "identical full batches",
            flush=True,
        )
    data = PackedDataset(
        args.data, tokenizer,
        batch_size // nproc if shard_data else batch_size, seq_len,
        eos_id=getattr(tokenizer, "eos_id", 0),
        seed=tc.seed,
        shard=jax.process_index() if shard_data else 0,
        num_shards=nproc if shard_data else 1,
    )
    batch_is_global = nproc > 1 and not shard_data
    print(
        f"training: {n_dev} devices, mesh={dict(zip(mesh.axis_names, mesh.devices.shape))}, "
        f"steps={steps}, corpus={data.n_tokens} tokens, lora_rank={lora_rank}",
        flush=True,
    )

    ckpt = CheckpointManager(
        os.path.join(args.out, "checkpoints"),
        save_steps=int(p.get("save_steps", max(1, steps // 5))),
    )
    # Preemption-safe resume (SURVEY.md §5): restore latest training state.
    trainable = trainer.lora if trainer.lora is not None else trainer.params
    abstract = {
        "trainable": jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            trainable,
        ),
        "opt_state": jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            trainer.opt_state,
        ),
    }
    resumed = ckpt.restore_latest(abstract)
    start_step = 0
    if resumed is not None:
        start_step, state = resumed
        if trainer.lora is not None:
            trainer.lora = state["trainable"]
        else:
            trainer.params = state["trainable"]
        trainer.opt_state = state["opt_state"]
        print(f"resumed from step {start_step}", flush=True)

    # Profiling window (SURVEY.md §5): params.json {"profile_steps": [a, b]}
    # captures a device trace of steps a..b into {out}/profile. The window
    # is clamped to the steps this run will actually execute (resume can
    # skip past it) and the trace always stops/flushes.
    prof_range = None
    prof = p.get("profile_steps")
    if prof and len(list(prof)) == 2:
        a, b = (int(x) for x in prof)
        a, b = max(a, start_step), min(b, steps - 1)
        if a <= b:
            prof_range = (a, b)
    elif prof:
        print(f"ignoring malformed profile_steps {prof!r} (need [start, end])")

    # Structured per-step telemetry (train/telemetry.py): step-time and
    # tokens/sec histograms + MFU on the shared registry, one JSON line per
    # log interval instead of bare prints. tokens_per_step is the GLOBAL
    # batch; train_step blocks on the loss, so the measured wall time is
    # the device step, not just dispatch.
    step_log = StepLogger(
        n_params=sum(
            getattr(x, "size", 0) for x in jax.tree.leaves(trainer.params)
        ),
        tokens_per_step=batch_size * seq_len,
        peak_flops=device_peak_flops(),
    )
    # Distributed tracing: the controller stamps a TRACEPARENT env var
    # into the training Job's container (controller/workloads.py), so this
    # run's spans — and every StepLogger JSON line, which carries the
    # active trace/span ids — join the trace that spawned it. The spans
    # export as JSONL next to the artifacts (or SUBSTRATUS_TRACE_EXPORT).
    from substratus_tpu.observability.propagation import context_from_env
    from substratus_tpu.observability.tracing import tracer

    tracing = False
    with tracer.span(
        "train.run", parent=context_from_env(),
        steps=steps, start_step=start_step, batch_size=batch_size,
        seq_len=seq_len, lora_rank=lora_rank,
    ):
        for step in range(start_step, steps):
            if prof_range and step == prof_range[0]:
                jax.profiler.start_trace(os.path.join(args.out, "profile"))
                tracing = True
            # Phase splits (train/telemetry.py): data_load / step /
            # checkpoint each timed separately, so a slow run triages to
            # input pipeline vs device step vs checkpoint I/O.
            t0 = time.perf_counter()
            batch = next(data)
            t_step = time.perf_counter()
            loss = trainer.train_step(batch, batch_is_global=batch_is_global)
            t_ckpt = time.perf_counter()
            if tracing and step == prof_range[1]:
                jax.profiler.stop_trace()
                tracing = False
            trainable = (
                trainer.lora if trainer.lora is not None else trainer.params
            )
            ckpt.maybe_save(
                step + 1,
                {"trainable": trainable, "opt_state": trainer.opt_state},
                force=(step == steps - 1),
            )
            t_end = time.perf_counter()
            step_log.log_step(
                step, float(loss), t_ckpt - t_step,
                last=step == steps - 1,
                data_seconds=t_step - t0,
                checkpoint_seconds=t_end - t_ckpt,
            )
    if tracing:
        jax.profiler.stop_trace()
    ckpt.close()
    try:
        tracer.export_jsonl(
            os.environ.get(
                "SUBSTRATUS_TRACE_EXPORT",
                os.path.join(args.out, "trace.jsonl"),
            )
        )
    except OSError as e:
        print(f"trace export failed (continuing): {e}", flush=True)

    final = (
        merge_lora(trainer.params, trainer.lora, trainer.lora_scale)
        if trainer.lora is not None
        else trainer.params
    )
    save_artifact(args.out, final, cfg, extra_meta={"trained_steps": steps})
    if trainer.lora is not None:
        # Alongside the merged model: the raw adapter as a multi-tenant
        # serving artifact (serve/adapters.py; docs/container-contract.md
        # "Adapter artifacts") — a Server sharing this model's base mounts
        # {artifacts}/adapter under /content/adapters/<tenant>.
        from substratus_tpu.serve.adapters import save_adapter_artifact

        save_adapter_artifact(
            os.path.join(args.out, "adapter"),
            trainer.lora,
            alpha=float(p.get("lora_alpha", 16.0)),
            rank=lora_rank,
            extra_meta={"trained_steps": steps},
        )
        print(f"adapter artifact saved to {args.out}/adapter", flush=True)
    print(f"artifact saved to {args.out}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
