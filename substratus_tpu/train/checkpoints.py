"""Orbax checkpointing: artifact format + preemption-safe training resume.

The reference treats checkpointing as a storage convention — artifacts at an
md5-addressed bucket path, `status.ready` short-circuits re-work, and
`save_steps` params are delegated to the external trainer image (SURVEY.md §5
"Checkpoint/resume"; cloud/common.go:45-66). Here it is a real subsystem:

  * artifact layout: `<dir>/substratus.json` (model config + metadata) next
    to an Orbax checkpoint tree — this is what `/content/artifacts` holds
    after a Model run and what a Server mounts at `/content/model`;
  * training: `CheckpointManager` saves (params | adapters) + opt state +
    step asynchronously every `save_steps`, keeps the newest checkpoints,
    and `restore_latest` resumes after preemption (TPU spot/maintenance
    events make this mandatory).
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from substratus_tpu.models.llama import CONFIGS, LlamaConfig, Params

META_FILE = "substratus.json"


def _cfg_to_dict(cfg: LlamaConfig) -> Dict[str, Any]:
    d = dataclasses.asdict(cfg)
    d["dtype"] = np.dtype(cfg.dtype).name if cfg.dtype is not None else "bfloat16"
    # attn_impl is an execution-context choice (mesh/hardware dependent),
    # not model architecture: never persist it into artifacts.
    d.pop("attn_impl", None)
    return d


def _cfg_from_dict(d: Dict[str, Any], family: str = "llama"):
    import jax.numpy as jnp

    from substratus_tpu.models import registry

    d = dict(d)
    d["dtype"] = jnp.dtype(d.get("dtype", "bfloat16"))
    return registry.config_class(family)(**d)


def save_artifact(
    path: str,
    params: Params,
    cfg,
    extra_meta: Optional[Dict[str, Any]] = None,
) -> None:
    """Write a servable model artifact: orbax params + config sidecar."""
    import orbax.checkpoint as ocp

    os.makedirs(path, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        # force=True: artifact writes are idempotent, matching the
        # reference's re-apply-into-existing-bucket semantics
        # (docs/design.md:139-160).
        ckptr.save(
            os.path.join(os.path.abspath(path), "params"), params, force=True
        )
    from substratus_tpu.models import registry

    meta = {
        "model_config": _cfg_to_dict(cfg),
        "family": registry.family_of(cfg),
        "format": "substratus-tpu-v1",
    }
    meta.update(extra_meta or {})
    with open(os.path.join(path, META_FILE), "w") as f:
        json.dump(meta, f, indent=2)


def maybe_restore_orbax(
    path: str, mesh=None, rules=None
) -> Optional[Tuple[LlamaConfig, Params]]:
    """Restore a save_artifact() dir; None if `path` isn't one (e.g. an HF
    checkpoint dir, which load/hf.py handles).

    Without a mesh the params land on the default device (single-chip
    serving); with a mesh they restore directly into the logical-axis
    shardings — artifacts written from an N-device training run restore onto
    any topology.
    """
    meta_path = os.path.join(path, META_FILE)
    if not os.path.exists(meta_path):
        return None
    import orbax.checkpoint as ocp
    from substratus_tpu.parallel.sharding import DEFAULT_RULES

    with open(meta_path) as f:
        meta = json.load(f)
    from substratus_tpu.models import registry

    family = registry.module_for(meta.get("family", "llama"))
    cfg = _cfg_from_dict(meta["model_config"], meta.get("family", "llama"))
    if meta.get("quantize") == "int8":
        from substratus_tpu.ops.quant import quantize_params

        shapes = jax.eval_shape(
            lambda: quantize_params(
                family.init_params(cfg, jax.random.key(0)),
                family.quant_contracting(cfg),
            )
        )
    else:
        shapes = jax.eval_shape(
            lambda: family.init_params(cfg, jax.random.key(0))
        )
    if mesh is not None:
        from substratus_tpu.parallel.sharding import sharding_tree

        shardings = sharding_tree(
            shapes, mesh, family.param_logical_axes(cfg), rules or DEFAULT_RULES
        )
    else:
        one = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        shardings = jax.tree.map(lambda _: one, shapes)
    shapes = jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        shardings,
    )
    with ocp.StandardCheckpointer() as ckptr:
        params = ckptr.restore(
            os.path.join(os.path.abspath(path), "params"), shapes
        )
    return cfg, params


class CheckpointManager:
    """Async training checkpoints with resume-latest semantics."""

    def __init__(self, directory: str, save_steps: int = 100, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self.save_steps = max(1, save_steps)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True
            ),
        )

    def maybe_save(self, step: int, state: Dict[str, Any], force: bool = False):
        if force or step % self.save_steps == 0:
            import orbax.checkpoint as ocp

            self._mgr.save(step, args=ocp.args.StandardSave(state))

    def restore_latest(
        self, abstract_state: Dict[str, Any]
    ) -> Optional[Tuple[int, Dict[str, Any]]]:
        import orbax.checkpoint as ocp

        step = self._mgr.latest_step()
        if step is None:
            return None
        state = self._mgr.restore(
            step, args=ocp.args.StandardRestore(abstract_state)
        )
        return step, state

    def close(self):
        self._mgr.wait_until_finished()
        self._mgr.close()
