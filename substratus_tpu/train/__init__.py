from substratus_tpu.train.trainer import (
    TrainConfig,
    Trainer,
    cross_entropy_loss,
)

__all__ = ["TrainConfig", "Trainer", "cross_entropy_loss"]
