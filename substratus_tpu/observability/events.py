"""Controller event stream: a Kubernetes-Event-shaped recorder.

The reference controllers used controller-runtime's EventRecorder to
narrate reconcile transitions (`kubectl get events` is the first thing an
operator reads when a CR sticks). This is the same surface rebuilt small:

  * `EVENTS.emit(reason, kind=..., name=..., ...)` from any plane;
  * identical events COUNT-DEDUPE (one entry, count++, lastTimestamp
    refreshed) exactly like the apiserver's event series compaction —
    a reconciler polling every 10 s must not mint 8640 objects a day;
  * the recorder is a bounded ring (oldest dropped) so a crash-looping
    controller can never OOM itself narrating the crash loop;
  * when a kube client is attached (Manager does this), every emit also
    upserts a real core/v1 Event object — visible to `kubectl get
    events` against a real cluster and to `sub events` against the fake;
  * the active trace id is stamped on each event, joining the event
    stream to the span exports (docs/observability.md).

Emission is best-effort end to end: a full ring or a failed kube write
drops telemetry, never a reconcile.
"""
from __future__ import annotations

import datetime
import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from substratus_tpu.observability.metrics import METRICS
from substratus_tpu.observability.tracing import tracer

log = logging.getLogger("substratus.events")

METRICS.describe(
    "substratus_events_total",
    "Events emitted through the shared recorder, by type (dedup counts "
    "each occurrence).", type="counter",
)

EVENT_SOURCE = "substratus-tpu"


def _iso(ts: float) -> str:
    return datetime.datetime.fromtimestamp(
        ts, datetime.timezone.utc
    ).strftime("%Y-%m-%dT%H:%M:%SZ")


class EventRecorder:
    """Bounded, count-deduplicating event sink (thread-safe)."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._events: "OrderedDict[tuple, Dict[str, Any]]" = OrderedDict()
        self._capacity = capacity
        self._kube = None
        self.dropped = 0  # events evicted by the ring since the last clear

    def attach_kube(self, client) -> None:
        """Write-through every future emit as a core/v1 Event object on
        this client (real cluster or FakeKube)."""
        self._kube = client

    def emit(
        self,
        reason: str,
        *,
        kind: str = "",
        name: str = "",
        namespace: str = "default",
        message: str = "",
        type: str = "Normal",  # noqa: A002 — the k8s field name
    ) -> Dict[str, Any]:
        """Record one event occurrence; returns the (possibly deduped)
        entry. Dedup key is everything but the timestamps/count."""
        now = time.time()
        ctx = tracer.current_context()
        key = (type, reason, kind, namespace, name, message)
        with self._lock:
            ev = self._events.get(key)
            if ev is not None:
                ev["count"] += 1
                ev["lastTimestamp"] = now
                if ctx is not None:
                    ev["trace_id"] = ctx.trace_id
                self._events.move_to_end(key)
            else:
                ev = {
                    "type": type,
                    "reason": reason,
                    "kind": kind,
                    "namespace": namespace,
                    "name": name,
                    "message": message,
                    "count": 1,
                    "firstTimestamp": now,
                    "lastTimestamp": now,
                    "trace_id": ctx.trace_id if ctx is not None else None,
                }
                self._events[key] = ev
                while len(self._events) > self._capacity:
                    self._events.popitem(last=False)
                    self.dropped += 1
            snapshot = dict(ev)
        METRICS.inc("substratus_events_total", {"type": type})
        self._publish(snapshot)
        return snapshot

    def recent(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Events newest-last-seen first (each with count/timestamps)."""
        with self._lock:
            out = [dict(e) for e in reversed(self._events.values())]
        return out[:limit] if limit else out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    # -- kube write-through -------------------------------------------------

    @staticmethod
    def _object_name(ev: Dict[str, Any]) -> str:
        import hashlib

        h = hashlib.sha256(
            "/".join(
                str(ev[k])
                for k in ("type", "reason", "kind", "namespace", "name",
                          "message")
            ).encode()
        ).hexdigest()[:12]
        base = ev["name"] or "cluster"
        return f"{base}.{h}"

    def to_kube_event(self, ev: Dict[str, Any]) -> Dict[str, Any]:
        """One recorder entry -> a core/v1 Event manifest."""
        return {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {
                "name": self._object_name(ev),
                "namespace": ev["namespace"] or "default",
            },
            "involvedObject": {
                "kind": ev["kind"],
                "namespace": ev["namespace"] or "default",
                "name": ev["name"],
            },
            "reason": ev["reason"],
            "message": ev["message"],
            "type": ev["type"],
            "count": ev["count"],
            "firstTimestamp": _iso(ev["firstTimestamp"]),
            "lastTimestamp": _iso(ev["lastTimestamp"]),
            "source": {"component": EVENT_SOURCE},
        }

    def _publish(self, ev: Dict[str, Any]) -> None:
        client = self._kube
        if client is None:
            return
        desired = self.to_kube_event(ev)
        md = desired["metadata"]
        try:
            live = client.get_or_none("Event", md["namespace"], md["name"])
            if live is None:
                client.create(desired)
            else:
                live.update(
                    {
                        k: desired[k]
                        for k in ("count", "lastTimestamp", "message",
                                  "reason", "type")
                    }
                )
                client.update(live)
        except Exception:  # sublint: allow[broad-except]: telemetry must never fail the work it observes
            log.debug("event write-through failed", exc_info=True)


EVENTS = EventRecorder()
