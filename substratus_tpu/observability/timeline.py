"""Engine step timeline: a bounded per-iteration flight recorder with
pipeline-bubble attribution.

The overlapped scheduler (docs/performance.md "Overlapped scheduling")
made steady-state inter-token latency ``max(device_step, host_work)``
— which means any residual gap above the device window is a *bubble*
the pipeline failed to hide, and nothing in the phase histograms says
WHY. This recorder closes that: the engine reports one record per
scheduler iteration (dispatch/drain/flush/admission timings, slot
occupancy), and the recorder attributes each iteration's gap over the
device floor to a cause:

  * ``host_overrun`` — the deferred drain + dispatch host work did not
    fit under the device window (the overlap win eroding);
  * ``flush`` — a metered pipeline flush (spec/gang/handoff/drain/
    preempt) forced a synchronous drain, idling the device;
  * ``admission_stall`` — prefill/admission ran while decodes waited;
  * ``pool_dry`` — admission held a request because the KV pool was
    dry (capacity, not host speed).

The attribution feeds ``substratus_serve_pipeline_bubble_seconds``
(counter, by cause) so a scrape can alert on host-path regressions,
and the ring renders as Chrome-trace JSON on ``GET /debug/stepz``
(load chrome://tracing or Perfetto on the payload).

The device floor: the configured ``step_floor_s`` when the engine
simulates a device window (CPU bench/smoke), else the minimum
iteration wall over a sliding window — self-calibrating against the
best the hardware recently did, so production bubbles are measured
against reality, not a config guess.

Thread contract: ``record_iteration`` is called by the engine
scheduler thread only; readers (``/debug/stepz``, the bench) snapshot
under the same lock.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

from substratus_tpu.observability.metrics import METRICS

METRICS.describe(
    "substratus_serve_pipeline_bubble_seconds",
    "Scheduler-iteration time above the device-step floor, attributed "
    "by cause (host_overrun|flush|admission_stall|pool_dry): the gap "
    "the overlapped pipeline failed to hide "
    "(docs/performance.md \"Pipeline-bubble attribution\").",
    type="counter",
)

BUBBLE_CAUSES = ("host_overrun", "flush", "admission_stall", "pool_dry")


class StepTimeline:
    """Bounded ring of per-iteration step records + bubble accounting."""

    def __init__(self, capacity: int = 512, floor_window: int = 64):
        if capacity < 1 or floor_window < 1:
            raise ValueError(
                f"invalid timeline shape: capacity={capacity} "
                f"floor_window={floor_window}"
            )
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._walls: deque = deque(maxlen=floor_window)
        self._seq = 0
        self._totals: Dict[str, float] = {c: 0.0 for c in BUBBLE_CAUSES}
        self._gap_s = 0.0
        self._unattributed_s = 0.0
        # Epoch pair: perf_counter timestamps in records map onto the
        # wall clock for Chrome-trace ts values.
        self._epoch_perf = time.perf_counter()
        self._epoch_wall = time.time()

    # -- writer (engine scheduler thread) ---------------------------------

    def record_iteration(
        self,
        *,
        t_start: float,
        wall_s: float,
        admit_s: float = 0.0,
        admitted: int = 0,
        dispatch_s: float = 0.0,
        drain_s: float = 0.0,
        drain_off_s: float = 0.0,
        flush_s: float = 0.0,
        flush_reasons: Sequence[str] = (),
        pool_dry: bool = False,
        active_slots: int = 0,
        max_slots: int = 1,
        configured_floor_s: float = 0.0,
    ) -> dict:
        """Record one scheduler iteration and attribute its bubble.

        Attribution walks the causes in blame order — flush first (a
        metered stall is the most specific explanation), then
        admission (pool_dry when the iteration held a request for
        pages), and the remainder to host_overrun whenever host work
        (dispatch/drain) actually ran this iteration. Anything left
        (an iteration that idled for none of the known reasons) is
        kept visible as ``unattributed`` rather than misfiled.
        """
        wall_s = max(0.0, float(wall_s))
        with self._lock:
            self._walls.append(wall_s)
            if configured_floor_s > 0.0:
                floor_s = float(configured_floor_s)
            else:
                floor_s = min(self._walls)
            gap = max(0.0, wall_s - floor_s)
            remaining = gap
            bubble: Dict[str, float] = {}

            def take(cause: str, amount: float) -> None:
                nonlocal remaining
                part = min(remaining, max(0.0, amount))
                if part <= 0.0:
                    return
                bubble[cause] = bubble.get(cause, 0.0) + part
                self._totals[cause] += part
                remaining -= part

            take("flush", flush_s)
            if pool_dry or admitted:
                # An empty-queue admission check costs microseconds and
                # is not a stall; only iterations that actually boarded
                # someone (or held a request for pages) bill admission.
                take("pool_dry" if pool_dry else "admission_stall",
                     admit_s)
            if remaining > 0.0 and (drain_s > 0.0 or dispatch_s > 0.0):
                take("host_overrun", remaining)
            self._gap_s += gap
            self._unattributed_s += remaining
            self._seq += 1
            rec = {
                "seq": self._seq,
                "t_start": round(t_start - self._epoch_perf, 6),
                "wall_s": round(wall_s, 6),
                "floor_s": round(floor_s, 6),
                "gap_s": round(gap, 6),
                "admit_s": round(admit_s, 6),
                "admitted": int(admitted),
                "dispatch_s": round(dispatch_s, 6),
                "drain_s": round(drain_s, 6),
                "drain_off_s": round(drain_off_s, 6),
                "flush_s": round(flush_s, 6),
                "flush_reasons": list(flush_reasons),
                "pool_dry": bool(pool_dry),
                "active_slots": int(active_slots),
                "occupancy": round(int(active_slots) / max(1, max_slots), 4),
                "bubble": {c: round(v, 6) for c, v in bubble.items()},
                "unattributed_s": round(remaining, 6),
            }
            self._ring.append(rec)
        for cause, part in bubble.items():
            METRICS.inc(
                "substratus_serve_pipeline_bubble_seconds",
                {"cause": cause}, by=part,
            )
        return rec

    # -- readers (debug endpoints, bench) ---------------------------------

    def records(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def bubble_totals(self) -> dict:
        """Lifetime accounting (NOT bounded by the ring): per-cause
        bubble seconds, the total measured gap, what stayed
        unattributed, and the iteration count."""
        with self._lock:
            attributed = sum(self._totals.values())
            return {
                "by_cause": {c: round(v, 6) for c, v in self._totals.items()},
                "attributed_s": round(attributed, 6),
                "gap_s": round(self._gap_s, 6),
                "unattributed_s": round(self._unattributed_s, 6),
                "attributed_frac": (
                    round(attributed / self._gap_s, 4)
                    if self._gap_s > 0.0 else 1.0
                ),
                "iterations": self._seq,
            }

    def floor_estimate(self) -> Optional[float]:
        with self._lock:
            return min(self._walls) if self._walls else None

    def chrome_trace(self) -> dict:
        """The ring as Chrome-trace JSON (``chrome://tracing`` /
        Perfetto load this directly). tid 0 = the scheduler iteration
        spans; tid 1 = host-side sub-spans (admission, deferred drain,
        flushes — placed at their measured offsets where known)."""
        with self._lock:
            recs = [dict(r) for r in self._ring]
            totals = {c: round(v, 6) for c, v in self._totals.items()}
            epoch_wall = self._epoch_wall
        events: List[dict] = [
            {"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "substratus-serve engine"}},
            {"name": "thread_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "scheduler iterations"}},
            {"name": "thread_name", "ph": "M", "pid": 0, "tid": 1,
             "args": {"name": "host work (admit/drain/flush)"}},
        ]
        for r in recs:
            ts = r["t_start"] * 1e6
            events.append({
                "name": "iteration", "cat": "engine", "ph": "X",
                "pid": 0, "tid": 0, "ts": round(ts, 1),
                "dur": round(r["wall_s"] * 1e6, 1),
                "args": {
                    "seq": r["seq"],
                    "floor_ms": round(r["floor_s"] * 1e3, 3),
                    "gap_ms": round(r["gap_s"] * 1e3, 3),
                    "bubble": r["bubble"],
                    "active_slots": r["active_slots"],
                    "occupancy": r["occupancy"],
                    "admitted": r["admitted"],
                    "flush_reasons": r["flush_reasons"],
                },
            })
            if r["admit_s"] > 0.0:
                events.append({
                    "name": "admit", "cat": "host", "ph": "X",
                    "pid": 0, "tid": 1, "ts": round(ts, 1),
                    "dur": round(r["admit_s"] * 1e6, 1),
                    "args": {"admitted": r["admitted"],
                             "pool_dry": r["pool_dry"]},
                })
            if r["drain_s"] > 0.0:
                events.append({
                    "name": "drain", "cat": "host", "ph": "X",
                    "pid": 0, "tid": 1,
                    "ts": round(ts + r["drain_off_s"] * 1e6, 1),
                    "dur": round(r["drain_s"] * 1e6, 1),
                    "args": {},
                })
            if r["flush_s"] > 0.0:
                events.append({
                    "name": "flush:" + ",".join(r["flush_reasons"]),
                    "cat": "host", "ph": "X", "pid": 0, "tid": 1,
                    # Flushes interleave dispatch/admission; the record
                    # carries only their summed duration, so the span is
                    # placed at the iteration start (approximate).
                    "ts": round(ts, 1),
                    "dur": round(r["flush_s"] * 1e6, 1),
                    "args": {"reasons": r["flush_reasons"]},
                })
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix_s": round(epoch_wall, 3),
                "iterations_recorded": len(recs),
                "bubble_totals_s": totals,
            },
        }
