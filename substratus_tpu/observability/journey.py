"""Per-request lifecycle journeys: the "why was THIS request slow" layer.

The fleet plane answers "how is the fleet doing" and the step timeline
answers "where does the engine lose time"; a ``RequestJourney`` answers
the per-request question — a bounded ring of typed lifecycle events
(submit, admit, prefill, handoff ship/install, drain rounds, spec
rounds, flushes, emits, terminal) stamped with monotonic timestamps on
whichever thread owns the request at that moment. Recording is pure
host work (a deque append + a counter), so the engine's zero-host-sync
dispatch contract is untouched: events for an overlapped dispatch are
stamped at drain, never inside the dispatch half.

Cross-process: the disagg handoff header carries a W3C traceparent
(serve/disagg.py), the decode engine parents its journey under it, and
the decode→prefill ``done`` back-channel frame returns the decode
journey segment (``to_wire``/``from_wire``) so the prefill side stitches
ONE merged journey spanning both processes. Timestamps on the wire are
epoch-anchored wall-clock microseconds (the StepTimeline convention), so
segments from different processes sort on a common axis — subject to
the hosts' clock sync, which is the same caveat every distributed
tracer carries.

Layering (docs/observability.md "Request journeys"):

  * every ``Request`` owns a ``RequestJourney`` (created at submit, or
    at KV-install on a decode-role engine);
  * each Engine holds a ``JourneyLog`` — a bounded ring of COMPLETED
    journeys served by ``/debug/requestz?id=`` — and a ``SlowRing`` of
    SLO-breaching journeys served by ``/debug/slowz``;
  * the gateway keeps its edge-side view (arrival, shed/hedge/retry,
    replica choice) in the same classes, keyed by ``x-trace-id``, and
    ``sub trace <id>`` joins all of it into one waterfall.

Jax-free; every structure is lock-guarded because completed journeys
are read from HTTP handler threads while the scheduler keeps recording.
"""
from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Mapping, Optional, Union

from substratus_tpu.observability.metrics import METRICS

METRICS.describe(
    "substratus_serve_journey_events_total",
    "Request-journey lifecycle events recorded, by event type "
    "(observability/journey.py).",
    type="counter",
)
METRICS.describe(
    "substratus_serve_slo_exemplars_total",
    "SLO-breach exemplars captured (trace id attached to the breaching "
    "latency histogram bucket and the journey copied to /debug/slowz).",
    type="counter",
)

# The full event-type catalog (docs/observability.md keeps the prose
# row per type; tests assert recorded types stay inside this set so a
# typo'd event name fails a test instead of fragmenting dashboards).
EVENT_TYPES = (
    "submit",        # request entered the engine queue (submitter thread)
    "admit",         # scheduler dequeued + admitted (carries queue wait)
    "adapter_wait",  # admission parked on an adapter load
    "pool_wait",     # admission parked on page-pool capacity
    "prefill",       # prompt prefill ran (tokens, chunks)
    "prefix_hit",    # prefix-cache pages reused at admission
    "ship",          # prefill side exported + shipped KV pages
    "kv_recv",       # decode side received the KV frame (reader thread)
    "install",       # decode scheduler installed the migration
    "dispatch",      # overlapped step dispatched (stamped at drain)
    "drain",         # overlapped step drained (one per emitted token)
    "spec_round",    # speculative round verified {k, accepted}
    "flush",         # pipeline flush hit this request {reason}
    "preempt",       # request was preempted back to the queue
    "requeue",       # disagg flight requeued for re-prefill
    "slo_breach",    # SLOTracker threshold breach {slo, seconds}
    "shed",          # gateway shed the request {reason}
    "replica",       # gateway picked a replica {url, score}
    "hedge",         # gateway launched a hedged attempt
    "retry",         # gateway retried after a replica failure
    "arrive",        # gateway edge arrival
    "emit",          # one token delivered to the client queue
    "swap",          # hot weight-swap landed mid-stream {version}
    "rollout",       # controller-driven rolling swap hit this replica
    "end",           # terminal: EOS / length / cancel / error {reason}
)


def _wall_us() -> int:
    return time.time_ns() // 1_000


class RequestJourney:
    """Bounded ring of (wall_us, type, data) lifecycle events plus a
    first-occurrence mark per event type.

    The ring holds the most recent ``cap`` events (a long stream's emit
    events evict the oldest emits); ``marks`` pins the FIRST occurrence
    of every type outside the ring, so the waterfall milestones —
    submit, admit, ship, install, first emit, end — survive any stream
    length. ``total`` counts everything ever recorded.
    """

    __slots__ = (
        "trace_id", "rid", "origin", "cap", "total", "events", "marks",
        "breaches", "_segments", "_lock", "_epoch_perf", "_epoch_wall_us",
    )

    def __init__(self, trace_id: Optional[str] = None,
                 rid: Optional[str] = None, origin: str = "engine",
                 cap: int = 256):
        self.trace_id = trace_id or uuid.uuid4().hex
        self.rid = rid
        self.origin = origin
        self.cap = max(8, int(cap))
        self.total = 0
        self.events: "deque" = deque(maxlen=self.cap)
        self.marks: Dict[str, list] = {}
        self.breaches: List[dict] = []
        self._segments: List[dict] = []
        self._lock = threading.Lock()
        # Wall/monotonic epoch pair: events are stamped from the
        # monotonic clock (cheap, never steps) and anchored to wall
        # time once, so wire timestamps from two processes sort on a
        # shared axis (the StepTimeline convention).
        self._epoch_perf = time.perf_counter()
        self._epoch_wall_us = _wall_us()

    # -- recording (owning thread) ----------------------------------------

    def _now_us(self) -> int:
        return self._epoch_wall_us + int(
            (time.perf_counter() - self._epoch_perf) * 1e6
        )

    def record(self, type: str, **data) -> None:
        """Append one event. Pure host work: a timestamp, a deque
        append, a counter — safe on the scheduler thread mid-step."""
        ts = self._now_us()
        ev = [ts, type, data or None]
        with self._lock:
            self.events.append(ev)
            self.total += 1
            if type not in self.marks:
                self.marks[type] = ev
        METRICS.inc(
            "substratus_serve_journey_events_total", {"type": type}
        )

    def record_once(self, type: str, **data) -> None:
        """Record only the first occurrence of ``type`` (wait-style
        events that would otherwise repeat every scheduler poll)."""
        with self._lock:
            seen = type in self.marks
        if not seen:
            self.record(type, **data)

    def breach(self, slo: str, seconds: float, threshold_s: float) -> None:
        """Note an SLO breach; the completed journey is then copied to
        the engine's SlowRing at terminal time."""
        with self._lock:
            self.breaches.append({
                "slo": slo,
                "seconds": round(seconds, 6),
                "threshold_s": threshold_s,
            })
        self.record("slo_breach", slo=slo, seconds=round(seconds, 6))

    @property
    def ended(self) -> bool:
        with self._lock:
            return "end" in self.marks

    # -- cross-process stitch ----------------------------------------------

    def to_wire(self, limit: int = 160) -> dict:
        """Compact wire form of this journey segment for the disagg
        ``done`` back-channel frame (key drift between this producer
        and ``from_wire`` is caught by analysis/protodrift.py)."""
        with self._lock:
            ev = list(self.events)[-limit:]
            return {
                "tid": self.trace_id,
                "rid": self.rid,
                "o": self.origin,
                "n": self.total,
                "mk": {k: list(v) for k, v in self.marks.items()},
                "ev": [list(e) for e in ev],
                "br": list(self.breaches),
            }

    @staticmethod
    def from_wire(seg: Mapping) -> Optional[dict]:
        """Wire segment -> snapshot-shaped dict, or None when the
        payload is malformed (a garbled frame must not poison the
        prefill-side journey)."""
        if not isinstance(seg, Mapping):
            return None
        tid = seg.get("tid")
        ev = seg.get("ev")
        if not isinstance(tid, str) or not isinstance(ev, list):
            return None
        marks = seg.get("mk")
        return {
            "trace_id": tid,
            "rid": seg.get("rid"),
            "origin": str(seg.get("o", "remote")),
            "total": int(seg.get("n", len(ev))),
            "events": [list(e) for e in ev if isinstance(e, list)],
            "marks": dict(marks) if isinstance(marks, Mapping) else {},
            "breaches": list(seg.get("br") or []),
            "segments": [],
        }

    def stitch(self, segment: Union[Mapping, dict, None]) -> bool:
        """Merge a remote journey segment (``to_wire`` output or an
        already-parsed snapshot) under this journey. Returns False when
        the segment is unusable."""
        if isinstance(segment, Mapping) and "events" in segment \
                and "trace_id" in segment:
            parsed: Optional[dict] = dict(segment)
        else:
            parsed = self.from_wire(segment) if segment is not None else None
        if parsed is None:
            return False
        with self._lock:
            self.breaches.extend(parsed.get("breaches") or [])
            self._segments.append(parsed)
        return True

    # -- reads (any thread) ------------------------------------------------

    def snapshot(self) -> dict:
        """Full JSON-safe view: own ring + marks + stitched segments."""
        with self._lock:
            return {
                "trace_id": self.trace_id,
                "rid": self.rid,
                "origin": self.origin,
                "total": self.total,
                "dropped": max(0, self.total - len(self.events)),
                "events": [list(e) for e in self.events],
                "marks": {k: list(v) for k, v in self.marks.items()},
                "breaches": list(self.breaches),
                "segments": [dict(s) for s in self._segments],
            }


# -- journey rendering --------------------------------------------------------


def _origins(snapshot: Mapping) -> List[dict]:
    """Flatten a stitched snapshot into per-origin event groups."""
    out = [dict(snapshot)]
    for seg in snapshot.get("segments") or []:
        out.append(dict(seg))
    return out


def waterfall(snapshot: Mapping) -> List[dict]:
    """One row per event across all origins, time-sorted: the
    edge→prefill→transfer→decode→emit view `sub trace` prints."""
    rows: List[dict] = []
    for part in _origins(snapshot):
        origin = part.get("origin", "?")
        for ev in part.get("events") or []:
            if not isinstance(ev, (list, tuple)) or len(ev) < 2:
                continue
            rows.append({
                "ts_us": int(ev[0]),
                "origin": origin,
                "type": str(ev[1]),
                "data": ev[2] if len(ev) > 2 else None,
            })
    rows.sort(key=lambda r: r["ts_us"])
    return rows


# Milestone pairs rendered as Chrome-trace duration slices; everything
# else shows as instant events on the origin's row.
_PHASES = (
    # (slice name, start mark, end marks in preference order)
    ("queue", "submit", ("admit", "end")),
    ("prefill", "admit", ("ship", "emit", "end")),
    ("handoff", "ship", ("install", "end")),
    ("decode", "install", ("end",)),
    ("stream", "emit", ("end",)),
)


def chrome_trace(snapshot: Mapping) -> dict:
    """chrome://tracing / Perfetto JSON for one (stitched) journey:
    instant events per lifecycle event plus derived phase slices from
    the milestone marks. Load via /debug/requestz?id=."""
    parts = _origins(snapshot)
    events: List[dict] = []
    # Merged mark table: first occurrence wins across origins so the
    # handoff slice spans the prefill "ship" and the decode "install".
    marks: Dict[str, list] = {}
    for part in parts:
        for k, v in (part.get("marks") or {}).items():
            if isinstance(v, (list, tuple)) and len(v) >= 2:
                if k not in marks or v[0] < marks[k][0]:
                    marks[k] = list(v)
    for tid, part in enumerate(parts):
        origin = part.get("origin", "?")
        events.append({
            "name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
            "args": {"name": f"{origin} ({part.get('rid') or '-'})"},
        })
        for ev in part.get("events") or []:
            if not isinstance(ev, (list, tuple)) or len(ev) < 2:
                continue
            events.append({
                "name": str(ev[1]), "ph": "i", "s": "t",
                "pid": 0, "tid": tid, "ts": int(ev[0]),
                "args": ev[2] if len(ev) > 2 and ev[2] else {},
            })
    for name, start, ends in _PHASES:
        if start not in marks:
            continue
        t0 = int(marks[start][0])
        t1 = None
        for e in ends:
            m = marks.get(e)
            if m is not None and int(m[0]) >= t0:
                t1 = int(m[0])
                break
        if t1 is None:
            continue
        events.append({
            "name": name, "ph": "X", "pid": 0, "tid": len(parts),
            "ts": t0, "dur": max(1, t1 - t0), "args": {},
        })
    events.append({
        "name": "thread_name", "ph": "M", "pid": 0, "tid": len(parts),
        "args": {"name": "phases"},
    })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "trace_id": snapshot.get("trace_id"),
            "rid": snapshot.get("rid"),
            "breaches": snapshot.get("breaches") or [],
        },
    }


# -- per-engine retention -----------------------------------------------------


class JourneyLog:
    """Bounded ring of journeys, found by trace id or request id.

    Holds completed snapshots (engine terminal path) or live
    ``RequestJourney`` objects (the gateway's edge view, snapshotted at
    read time). Lock-guarded: the scheduler/manager threads add while
    HTTP handler threads search.
    """

    def __init__(self, cap: int = 128):
        self._lock = threading.Lock()
        self._ring: "deque" = deque(maxlen=max(1, int(cap)))

    def add(self, item: Union[RequestJourney, dict]) -> None:
        with self._lock:
            self._ring.append(item)

    def _snap(self, item) -> dict:
        return item.snapshot() if isinstance(item, RequestJourney) else item

    def live(self, trace_id: str) -> Optional[RequestJourney]:
        """The stored journey OBJECT for a trace id (gateway edge
        recording appends events to it as routing decisions happen)."""
        with self._lock:
            for item in reversed(self._ring):
                if isinstance(item, RequestJourney) \
                        and item.trace_id == trace_id:
                    return item
        return None

    def find(self, id: str) -> Optional[dict]:
        """Newest journey whose trace id or request id matches."""
        if not id:
            return None
        with self._lock:
            items = list(self._ring)
        for item in reversed(items):
            snap = self._snap(item)
            if snap.get("trace_id") == id or snap.get("rid") == id:
                return snap
        return None

    def snapshot(self, limit: int = 32) -> List[dict]:
        with self._lock:
            items = list(self._ring)[-limit:]
        return [self._snap(i) for i in items]

    def ids(self) -> List[dict]:
        with self._lock:
            items = list(self._ring)
        return [
            {"trace_id": self._snap(i).get("trace_id"),
             "rid": self._snap(i).get("rid")}
            for i in items
        ]


class SlowRing:
    """Bounded ring of SLO-breaching completed journeys — the
    /debug/slowz exemplar store. A breach marks the journey; the
    engine copies the COMPLETED journey here at terminal time, so every
    entry shows the request's whole lifecycle, not a prefix."""

    def __init__(self, cap: int = 32):
        self._lock = threading.Lock()
        self._ring: "deque" = deque(maxlen=max(1, int(cap)))
        self.total = 0

    def add(self, snapshot: dict) -> None:
        with self._lock:
            self._ring.append({
                "trace_id": snapshot.get("trace_id"),
                "rid": snapshot.get("rid"),
                "breaches": snapshot.get("breaches") or [],
                "journey": snapshot,
            })
            self.total += 1

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]
