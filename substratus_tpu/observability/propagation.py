"""W3C Trace Context propagation (traceparent) for cross-process tracing.

One request crosses many boundaries here — CLI -> serve HTTP -> engine
scheduler thread -> SCI gRPC -> spawned train/load Jobs — and each hop has
a different carrier. This module is the single codec for all of them:

  * HTTP: the ``traceparent`` request header (W3C Trace Context level 1),
    parsed by serve/server.py's middleware and injected by the CLI's
    urllib calls;
  * gRPC: the same value as ``traceparent`` invocation metadata
    (sci/grpc_transport.py, both directions);
  * processes: the ``TRACEPARENT`` environment variable (the convention
    OTel uses for batch jobs), read at train/main.py / load/main.py /
    sci/server_main.py startup;
  * Kubernetes workloads: a DETERMINISTIC traceparent derived from the
    owning CR's identity (controller/workloads.py) — reconcile passes
    mint fresh span ids every time, and stamping those into a pod spec
    would read as drift and recreate the Job on every pass, so the env
    value must be stable for the CR's lifetime.

Format: ``00-{trace_id:32hex}-{span_id:16hex}-{flags:2hex}``. Parsing is
strict per spec: unknown versions other than ff are accepted (forward
compat), all-zero ids are invalid, wrong field widths are invalid. A bad
header yields None — propagation must never fail a request.
"""
from __future__ import annotations

import hashlib
import os
import re
from typing import Dict, Mapping, Optional

from substratus_tpu.observability.tracing import SpanContext, tracer

TRACEPARENT_HEADER = "traceparent"
TRACEPARENT_ENV = "TRACEPARENT"

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def format_traceparent(ctx: SpanContext) -> str:
    """SpanContext -> traceparent value (always version 00, sampled)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """traceparent value -> SpanContext, or None when absent/malformed.
    Never raises: a hostile or truncated header degrades to 'no remote
    parent', not a 500."""
    if not value or not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, _flags = m.groups()
    if version == "ff":  # forbidden by the spec
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id, span_id)


def current_traceparent() -> Optional[str]:
    """traceparent for the active span, or None outside any span."""
    ctx = tracer.current_context()
    return format_traceparent(ctx) if ctx is not None else None


def inject_headers(headers: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Add the active span's traceparent to an outgoing header dict (the
    dict is returned for chaining; no span active -> unchanged)."""
    headers = dict(headers or {})
    tp = current_traceparent()
    if tp is not None:
        headers[TRACEPARENT_HEADER] = tp
    return headers


def context_from_env(
    environ: Optional[Mapping[str, str]] = None,
) -> Optional[SpanContext]:
    """Parent context from the TRACEPARENT env var (spawned-job carrier)."""
    env = os.environ if environ is None else environ
    return parse_traceparent(env.get(TRACEPARENT_ENV))


def deterministic_traceparent(*parts: str) -> str:
    """A traceparent derived from stable identity strings (e.g. a CR's
    kind/namespace/name/uid). Same inputs -> same value, so stamping it
    into a pod template never reads as spec drift. The span id half names
    a span that no exporter will ever contain — trace_lint treats absent
    parents as remote, by design."""
    h = hashlib.sha256("/".join(parts).encode()).hexdigest()
    trace_id, span_id = h[:32], h[32:48]
    # The spec forbids all-zero ids; a sha256 prefix of zeros is
    # astronomically unlikely but cheap to guard.
    if trace_id == "0" * 32:
        trace_id = "1" + trace_id[1:]
    if span_id == "0" * 16:
        span_id = "1" + span_id[1:]
    return f"00-{trace_id}-{span_id}-01"
