"""Shared telemetry registry: counters, gauges, fixed-bucket histograms.

One process-global registry (`METRICS`) serves every plane — the serve
engine's request latencies, the train loop's step times, the controller
Manager's reconcile counters — in Prometheus text exposition format 0.0.4,
so a single scrape config covers controller, serving, and training pods
identically (the reference only ever exposed controller-runtime's registry
behind kube-rbac-proxy; SURVEY.md §5).

No client library: the format is lines of `name{labels} value` plus
`# HELP`/`# TYPE` headers, and histograms are three derived series
(`_bucket` with cumulative `le` counts, `_sum`, `_count`) — ~200 lines of
stdlib beats a dependency the image doesn't carry.

Labels are passed as dicts (`{"kind": "Model"}`) and values are escaped per
the exposition spec (backslash, double-quote, newline). Legacy callers that
pass a pre-rendered label string keep working, unescaped, as before.
"""
from __future__ import annotations

import math
import re
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

Labels = Union[str, Mapping[str, object], None]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default latency buckets (seconds): spans sub-ms token gaps up to
# multi-minute train steps; quantile error is bounded by bucket width.
LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
# Occupancy / utilization ratios in [0, 1].
RATIO_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)
# Throughput (tokens/sec): decades with a 1-2.5-5 ladder.
THROUGHPUT_BUCKETS = (
    10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1_000.0, 2_500.0, 5_000.0,
    10_000.0, 25_000.0, 50_000.0, 100_000.0, 250_000.0, 1_000_000.0,
)


def escape_label_value(value: object) -> str:
    """Exposition-format label value escaping: \\ " and newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(value: float) -> str:
    """Canonical sample rendering: integer-valued samples print without a
    trailing `.0`, so a counter scraped as `5` never drifts to `5.0` when a
    later `inc(by=0.5)`-style caller turns the stored value into a float."""
    f = float(value)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 2**53:
        return str(int(f))
    return repr(f)


def _fmt_le(bound: float) -> str:
    if math.isinf(bound):
        return "+Inf"
    if float(bound).is_integer():
        return _fmt_value(bound)
    return "%.12g" % bound


def _labelstr(labels: Labels) -> str:
    """Canonical inner label string. Dicts are validated + escaped and
    sorted (so {"a":1,"b":2} and {"b":2,"a":1} are the same series); legacy
    pre-rendered strings pass through untouched."""
    if not labels:
        return ""
    if isinstance(labels, str):
        return labels
    parts = []
    for k in sorted(labels):
        if not _LABEL_RE.match(k):
            raise ValueError(f"invalid label name {k!r}")
        parts.append(f'{k}="{escape_label_value(labels[k])}"')
    return ",".join(parts)


class _Hist:
    """One histogram series: cumulative bucket counts + sum + count,
    plus an optional per-bucket exemplar (last trace id observed into
    the bucket WITH an exemplar — OpenMetrics semantics; the 0.0.4 text
    exposition cannot carry them, so they surface via the
    ``exemplars()`` read API / debug JSON instead)."""

    __slots__ = ("counts", "sum", "count", "exemplars")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative) counts
        self.sum = 0.0
        self.count = 0
        self.exemplars: Optional[Dict[int, dict]] = None  # bucket idx -> ex


class Metrics:
    """Process-global metric registry, Prometheus text format 0.0.4."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[Tuple[str, str], float] = {}  # counters+gauges
        self._types: Dict[str, str] = {}  # family -> counter|gauge|histogram
        self._help: Dict[str, str] = {}
        self._buckets: Dict[str, Tuple[float, ...]] = {}
        self._hists: Dict[Tuple[str, str], _Hist] = {}

    # -- registration ------------------------------------------------------

    def _family(self, name: str, kind: str) -> None:
        """Bind `name` to a metric kind; a name can never change kind (a
        scrape with `foo` as both gauge and histogram is unparseable)."""
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        have = self._types.get(name)
        if have is None:
            self._types[name] = kind
        elif have != kind:
            raise ValueError(
                f"metric {name!r} is a {have}, not a {kind}"
            )

    def describe(self, name: str, help: str, type: Optional[str] = None) -> None:
        """Attach HELP text (and optionally pre-declare the type)."""
        with self._lock:
            if type is not None:
                if type not in ("counter", "gauge", "histogram"):
                    raise ValueError(f"unknown metric type {type!r}")
                self._family(name, type)
            elif not _NAME_RE.match(name):
                raise ValueError(f"invalid metric name {name!r}")
            self._help[name] = help

    def histogram(
        self, name: str, help: str = "",
        buckets: Sequence[float] = LATENCY_BUCKETS,
    ) -> "Histogram":
        """Declare a histogram family (idempotent) and return a handle."""
        bs = tuple(sorted(float(b) for b in buckets))
        if not bs:
            raise ValueError("histogram needs at least one bucket")
        with self._lock:
            self._family(name, "histogram")
            if name in self._buckets and self._buckets[name] != bs:
                raise ValueError(
                    f"histogram {name!r} already declared with different "
                    "buckets"
                )
            self._buckets[name] = bs
            if help:
                self._help[name] = help
        return Histogram(self, name)

    # -- writes ------------------------------------------------------------

    def inc(self, name: str, labels: Labels = "", by: float = 1.0) -> None:
        key = (name, _labelstr(labels))
        with self._lock:
            self._family(name, "counter")
            self.counters[key] = self.counters.get(key, 0.0) + by

    def set(self, name: str, value: float, labels: Labels = "") -> None:
        with self._lock:
            self._family(name, "gauge")
            self.counters[(name, _labelstr(labels))] = value

    def observe(
        self, name: str, value: float, labels: Labels = "",
        buckets: Optional[Sequence[float]] = None,
        exemplar: Optional[str] = None,
    ) -> None:
        """Record `value` into the `name` histogram (declared on first use;
        `buckets` applies only then). `exemplar` attaches a trace id to
        the bucket this observation lands in (OpenMetrics-style; last
        writer wins per bucket) — dashboards jump from a p99 bucket to
        the offending request's journey through it."""
        key = (name, _labelstr(labels))
        with self._lock:
            self._family(name, "histogram")
            bs = self._buckets.get(name)
            if bs is None:
                bs = tuple(
                    sorted(float(b) for b in (buckets or LATENCY_BUCKETS))
                )
                self._buckets[name] = bs
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = _Hist(len(bs) + 1)  # +1: +Inf
            v = float(value)
            i = len(bs)  # +Inf bucket
            for j, b in enumerate(bs):
                if v <= b:
                    i = j
                    break
            h.counts[i] += 1
            h.sum += v
            h.count += 1
            if exemplar is not None:
                if h.exemplars is None:
                    h.exemplars = {}
                h.exemplars[i] = {
                    "trace_id": str(exemplar),
                    "value": v,
                    "ts": time.time(),
                }

    # -- reads -------------------------------------------------------------

    def get(self, name: str, labels: Labels = "") -> Optional[float]:
        """Current counter/gauge value, or a histogram's observation count."""
        key = (name, _labelstr(labels))
        with self._lock:
            if key in self._hists:
                return float(self._hists[key].count)
            return self.counters.get(key)

    def histogram_series(self, name: str) -> Dict[str, dict]:
        """Snapshot of one histogram family, keyed by the canonical label
        string ("" for unlabeled):

            {label_str: {"buckets": [(le, cumulative_count), ...,
                         (inf, count)], "sum": float, "count": int}}

        Empty dict when the family is unknown or has no observations.
        This is the read API behind /debug/perfz and the gang bench —
        consumers get the same cumulative-bucket data a Prometheus scrape
        would, without parsing the text exposition."""
        with self._lock:
            bs = self._buckets.get(name)
            if bs is None:
                return {}
            out: Dict[str, dict] = {}
            for (n, ls), h in self._hists.items():
                if n != name:
                    continue
                cum = 0
                buckets = []
                for bound, c in zip(tuple(bs) + (math.inf,), h.counts):
                    cum += c
                    buckets.append((bound, cum))
                out[ls] = {"buckets": buckets, "sum": h.sum, "count": h.count}
            return out

    def exemplars(self, name: str, labels: Labels = "") -> Dict[str, dict]:
        """Exemplars attached to one histogram series, keyed by the
        bucket's `le` rendering:

            {"0.25": {"trace_id": ..., "value": ..., "ts": ...}, ...}

        Empty when the series is unknown or nothing carried an
        exemplar. The text exposition stays format 0.0.4 (no `# {...}`
        suffixes); this read API + the debug planes are the carrier."""
        key = (name, _labelstr(labels))
        with self._lock:
            h = self._hists.get(key)
            bs = self._buckets.get(name)
            if h is None or bs is None or not h.exemplars:
                return {}
            bounds = tuple(bs) + (math.inf,)
            return {
                _fmt_le(bounds[i]): dict(ex)
                for i, ex in h.exemplars.items()
            }

    def remove(self, name: str, labels: Labels = "") -> None:
        """Drop ONE series (the family's declaration stays). For
        replica-labeled gauges whose replica left the fleet
        (gateway/fleet.py eviction) — a dead replica's last value would
        otherwise be scraped forever as if it were current."""
        key = (name, _labelstr(labels))
        with self._lock:
            self.counters.pop(key, None)
            self._hists.pop(key, None)

    def reset(self) -> None:
        """Drop every series and declaration (test isolation)."""
        with self._lock:
            self.counters.clear()
            self._types.clear()
            self._help.clear()
            self._buckets.clear()
            self._hists.clear()

    def render(self) -> str:
        with self._lock:
            by_family: Dict[str, List[Tuple[str, str]]] = {}
            for (name, labels), value in self.counters.items():
                by_family.setdefault(name, []).append(
                    (labels, _fmt_value(value))
                )
            lines: List[str] = []
            for name in sorted(set(by_family) | {n for n, _ in self._hists}):
                kind = self._types.get(name, "gauge")
                help_ = self._help.get(name, name)
                lines.append(f"# HELP {name} {_escape_help(help_)}")
                lines.append(f"# TYPE {name} {kind}")
                if kind == "histogram":
                    series = sorted(
                        (ls, h) for (n, ls), h in self._hists.items()
                        if n == name
                    )
                    bs = self._buckets[name]
                    for ls, h in series:
                        cum = 0
                        for bound, c in zip(
                            tuple(bs) + (math.inf,), h.counts
                        ):
                            cum += c
                            le = f'le="{_fmt_le(bound)}"'
                            lab = f"{ls},{le}" if ls else le
                            lines.append(f"{name}_bucket{{{lab}}} {cum}")
                        lines.append(
                            f"{name}_sum{{{ls}}} {_fmt_value(h.sum)}"
                            if ls else f"{name}_sum {_fmt_value(h.sum)}"
                        )
                        lines.append(
                            f"{name}_count{{{ls}}} {h.count}"
                            if ls else f"{name}_count {h.count}"
                        )
                else:
                    for ls, v in sorted(by_family.get(name, [])):
                        lines.append(
                            f"{name}{{{ls}}} {v}" if ls else f"{name} {v}"
                        )
            return "\n".join(lines) + "\n"


class Histogram:
    """Thin handle onto a registry histogram family (`Metrics.histogram`)."""

    def __init__(self, registry: Metrics, name: str):
        self.registry = registry
        self.name = name

    def observe(self, value: float, labels: Labels = "") -> None:
        self.registry.observe(self.name, value, labels)


def quantile_from_buckets(buckets, q: float) -> Optional[float]:
    """Prometheus-style histogram_quantile over cumulative buckets
    ([(le, cumulative_count), ...] as returned by histogram_series,
    final bound +Inf): linear interpolation inside the bucket holding
    rank q*count. Returns None for an empty histogram; observations in
    the +Inf bucket clamp to the last finite bound (same convention as
    PromQL — the histogram cannot say more than its widest bucket)."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in buckets:
        if cum >= rank:
            if math.isinf(bound):
                return prev_bound
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, cum
    return prev_bound


METRICS = Metrics()


# -- exposition lint (hack/metrics_lint.py + tests) --------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (-?[0-9]+(\.[0-9]+)?"
    r"(e[+-]?[0-9]+)?|[+-]Inf|NaN)$"
)
_LABELS_RE = re.compile(
    r'^[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*$'
)


def lint_exposition(text: str) -> List[str]:
    """Validate Prometheus text-format output; returns a list of problems
    (empty = clean). Checks: every sample parses, label values are escaped,
    every family has exactly one HELP and one TYPE emitted before its
    samples, histogram families emit _bucket/_sum/_count with a +Inf
    bucket, and no family is declared twice."""
    problems: List[str] = []
    helped: set = set()
    typed: Dict[str, str] = {}
    sampled: set = set()
    for ln, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            name = parts[2] if len(parts) >= 3 else ""
            if name in helped:
                problems.append(f"line {ln}: duplicate HELP for {name}")
            helped.add(name)
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ")
            if len(parts) != 4 or parts[3] not in (
                "counter", "gauge", "histogram", "summary", "untyped"
            ):
                problems.append(f"line {ln}: malformed TYPE: {line!r}")
                continue
            name = parts[2]
            if name in typed:
                problems.append(f"line {ln}: duplicate TYPE for {name}")
            if name in sampled:
                problems.append(
                    f"line {ln}: TYPE for {name} after its samples"
                )
            typed[name] = parts[3]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {ln}: unparseable sample: {line!r}")
            continue
        name, labels = m.group(1), m.group(3)
        if labels and not _LABELS_RE.match(labels):
            problems.append(f"line {ln}: bad label syntax: {labels!r}")
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and typed.get(base) == "histogram":
                family = base
        sampled.add(family)
        if family not in typed:
            problems.append(f"line {ln}: sample {name} has no TYPE")
        if family not in helped:
            problems.append(f"line {ln}: sample {name} has no HELP")
    for name in typed:
        if typed[name] == "histogram" and name in sampled:
            if f'{name}_bucket' not in text or "+Inf" not in text:
                problems.append(f"histogram {name} missing +Inf bucket")
    return problems
