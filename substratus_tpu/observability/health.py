"""healthz/readyz probes + Prometheus-format metrics.

Reference parity: controller-runtime serves /healthz,/readyz (main.go:227-234)
and Prometheus metrics behind kube-rbac-proxy (SURVEY.md §5). Here a single
stdlib HTTP endpoint serves both; metrics are text-format counters the
Manager updates (reconcile totals/errors/queue depth) — scrape-compatible
without a client library.
"""
from __future__ import annotations

import http.server
import threading
from typing import Optional


class Metrics:
    """Process-global counters, exposed in Prometheus text format."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {}

    def inc(self, name: str, labels: str = "", by: float = 1.0) -> None:
        with self._lock:
            key = (name, labels)
            self.counters[key] = self.counters.get(key, 0.0) + by

    def set(self, name: str, value: float, labels: str = "") -> None:
        with self._lock:
            self.counters[(name, labels)] = value

    def render(self) -> str:
        with self._lock:
            lines = []
            for (name, labels), value in sorted(self.counters.items()):
                lines.append(
                    f"{name}{{{labels}}} {value}" if labels else f"{name} {value}"
                )
            return "\n".join(lines) + "\n"


METRICS = Metrics()


def serve_health(
    port: int = 8081, manager=None, block: bool = False
) -> http.server.ThreadingHTTPServer:
    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                body = b"ok"
                self.send_response(200)
            elif self.path == "/metrics":
                if manager is not None:
                    with manager._lock:
                        METRICS.set(
                            "substratus_workqueue_depth", len(manager._queue)
                        )
                body = METRICS.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
            else:
                body = b"not found"
                self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    if block:
        server.serve_forever()
    else:
        threading.Thread(target=server.serve_forever, daemon=True).start()
    return server
