"""healthz/readyz probes + Prometheus-format metrics.

Reference parity: controller-runtime serves /healthz,/readyz (main.go:227-234)
and Prometheus metrics behind kube-rbac-proxy (SURVEY.md §5). Here a single
stdlib HTTP endpoint serves both; metrics are text-format counters the
Manager updates (reconcile totals/errors/queue depth) — scrape-compatible
without a client library. Passing an authorizer (observability/authz.py)
RBAC-protects /metrics exactly as the reference's kube-rbac-proxy sidecar
does; `tls=True` serves HTTPS with a self-signed cert (the ServiceMonitor
scrapes with insecureSkipVerify, reference config/prometheus/monitor.yaml).
"""
from __future__ import annotations

import http.server
import logging
import ssl
import tempfile
import threading
from typing import Optional

# The registry moved to observability/metrics.py (HELP/TYPE exposition,
# label escaping, histograms); re-exported here for existing deep imports.
from substratus_tpu.observability.metrics import METRICS, Metrics  # noqa: F401


def serve_health(
    port: int = 8081, manager=None, block: bool = False,
    authorizer=None, tls: bool = False, expose_metrics: bool = True,
) -> http.server.ThreadingHTTPServer:
    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path in ("/healthz", "/readyz"):
                body = b"ok"
                self.send_response(200)
            elif self.path == "/metrics" and not expose_metrics:
                # A protected listener owns /metrics; serving it here too
                # would let anyone bypass the RBAC check via the probe port.
                body = b"metrics are served on the authenticated port"
                self.send_response(403)
            elif self.path == "/metrics":
                if authorizer is not None:
                    status, reason = authorizer.allow(
                        self.headers.get("Authorization")
                    )
                    if status != 200:
                        body = reason.encode()
                        self.send_response(status)
                        if status == 401:
                            self.send_header("WWW-Authenticate", "Bearer")
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                if manager is not None:
                    with manager._lock:
                        METRICS.set(
                            "substratus_workqueue_depth", len(manager._queue)
                        )
                body = METRICS.render().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
            else:
                body = b"not found"
                self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    if tls:
        ctx = _tls_context()
        if ctx is None:
            # Never degrade to plaintext: scraper ServiceAccount bearer
            # tokens would cross the wire unencrypted. kube-rbac-proxy
            # refuses to start in the same situation.
            raise RuntimeError(
                "tls=True but no TLS backend is available (cryptography "
                "package or openssl binary required); refusing to serve "
                "bearer-token-authenticated metrics over plain HTTP"
            )

        class Server(http.server.ThreadingHTTPServer):
            # Handshake runs in the per-connection thread (finish_request),
            # never in the accept loop: a client that connects and stalls
            # must not wedge the listener for every later scrape.
            def finish_request(self, request, client_address):
                request.settimeout(10)
                request = ctx.wrap_socket(request, server_side=True)
                self.RequestHandlerClass(request, client_address, self)

            def handle_error(self, request, client_address):
                pass  # handshake garbage from scanners is routine

        server = Server(("0.0.0.0", port), Handler)
    else:
        server = http.server.ThreadingHTTPServer(("0.0.0.0", port), Handler)
    if block:
        server.serve_forever()
    else:
        threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def _tls_context() -> Optional[ssl.SSLContext]:
    """TLS context with an ephemeral self-signed cert (the scraper uses
    insecureSkipVerify; TLS here is for token confidentiality on the wire,
    matching kube-rbac-proxy's --secure-listen-address). Cert generation
    prefers the `cryptography` package, falls back to the openssl binary,
    and returns None when neither exists (caller refuses to serve)."""
    pem = _selfsigned_pem()
    if pem is None:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    with tempfile.NamedTemporaryFile(suffix=".pem") as f:
        f.write(pem)
        f.flush()
        ctx.load_cert_chain(f.name)
    return ctx


def _selfsigned_pem() -> Optional[bytes]:
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
        import datetime

        key = ec.generate_private_key(ec.SECP256R1())
        name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, "substratus-metrics")]
        )
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=3650))
            .sign(key, hashes.SHA256())
        )
        return key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.PKCS8,
            serialization.NoEncryption(),
        ) + cert.public_bytes(serialization.Encoding.PEM)
    except ImportError:
        pass
    import shutil
    import subprocess

    if shutil.which("openssl") is None:
        return None
    with tempfile.TemporaryDirectory() as d:
        try:
            subprocess.run(
                ["openssl", "req", "-x509", "-newkey", "ec",
                 "-pkeyopt", "ec_paramgen_curve:prime256v1", "-nodes",
                 "-keyout", f"{d}/key.pem", "-out", f"{d}/cert.pem",
                 "-days", "3650", "-subj", "/CN=substratus-metrics"],
                check=True, capture_output=True, timeout=30,
            )
        except (subprocess.SubprocessError, OSError):
            return None
        with open(f"{d}/key.pem", "rb") as kf, open(f"{d}/cert.pem", "rb") as cf:
            return kf.read() + cf.read()
