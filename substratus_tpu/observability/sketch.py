"""Mergeable fixed-bucket percentile sketches + SLO burn tracking.

The serving SLOs (TTFT, inter-token latency) need percentiles that
aggregate across a fleet: a replica cannot ship raw samples on every
load report, and you cannot average percentiles. A fixed-bucket sketch
CAN be merged exactly — two sketches over the same bucket bounds add
counts bucket-wise, and the merged quantile is what a single sketch
over the union of samples would have said (bounded by bucket width,
the same error a Prometheus histogram_quantile carries). That is why
the bounds are fixed at declaration and merging across different
bounds is an error, never an approximation.

Layering (docs/observability.md "Fleet telemetry"):

  * each Engine holds an ``SLOTracker`` — one ``Sketch`` per SLO plus a
    burn counter (`substratus_slo_burn_total{slo=...}`) incremented on
    every observation over the threshold;
  * ``Engine.load_snapshot()`` carries ``SLOTracker.snapshot()`` (the
    serialized sketches), so every ``GET /loadz`` poll ships the
    replica's full latency distribution in a few hundred bytes;
  * the gateway's fleet aggregator (gateway/fleet.py) keeps the latest
    sketch per replica and merges them into fleet-wide percentiles —
    exact aggregation, no per-request work on the gateway.

Jax-free and lock-guarded: observed on the engine scheduler thread,
snapshotted from HTTP handler threads.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from substratus_tpu.observability.metrics import (
    LATENCY_BUCKETS,
    METRICS,
    quantile_from_buckets,
)

METRICS.describe(
    "substratus_slo_burn_total",
    "Observations over their SLO threshold, by slo (ttft|inter_token): "
    "the error-budget burn counter a controller alerts and scales on.",
    type="counter",
)

# Default SLO thresholds (seconds). Deliberately generous: a burn
# counter that ticks on every token is noise, one that ticks when the
# user-visible contract breaks is a signal (EngineConfig overrides).
DEFAULT_SLOS: Tuple[Tuple[str, float], ...] = (
    ("ttft", 2.0),
    ("inter_token", 0.25),
)


class Sketch:
    """Fixed-bucket latency sketch: counts per bucket + sum + count.

    Mergeable by construction — see the module docstring. Bounds
    default to the registry's LATENCY_BUCKETS so sketch percentiles
    and scraped histogram percentiles agree bucket-for-bucket.
    """

    def __init__(self, bounds: Sequence[float] = LATENCY_BUCKETS):
        bs = tuple(sorted(float(b) for b in bounds))
        if not bs:
            raise ValueError("sketch needs at least one bucket bound")
        self.bounds = bs
        self._lock = threading.Lock()
        self._counts = [0] * (len(bs) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        v = float(value)
        i = len(self.bounds)
        for j, b in enumerate(self.bounds):
            if v <= b:
                i = j
                break
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def merge(self, other: "Sketch") -> None:
        """Add another sketch's counts into this one (exact: the result
        is the sketch of the combined sample set)."""
        if other.bounds != self.bounds:
            raise ValueError(
                "cannot merge sketches with different bucket bounds "
                f"({len(other.bounds)} vs {len(self.bounds)} bounds)"
            )
        with other._lock:
            counts = list(other._counts)
            s, n = other._sum, other._count
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._sum += s
            self._count += n

    def quantile(self, q: float) -> Optional[float]:
        """PromQL-convention quantile (linear interpolation inside the
        holding bucket; +Inf clamps to the widest bound). None = empty."""
        import math

        with self._lock:
            counts = list(self._counts)
        cum = 0
        buckets: List[tuple] = []
        for bound, c in zip(self.bounds + (math.inf,), counts):
            cum += c
            buckets.append((bound, cum))
        return quantile_from_buckets(buckets, q)

    def to_dict(self) -> dict:
        """Wire form for load snapshots: bounds + per-bucket counts
        (non-cumulative, last entry = +Inf bucket) + sum + count."""
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "sum": round(self._sum, 6),
                "count": self._count,
            }

    @classmethod
    def from_dict(cls, d: Mapping) -> "Sketch":
        """Rebuild from ``to_dict()`` output; raises ValueError on a
        malformed payload (a garbled report must not poison a merge)."""
        bounds = d.get("bounds")
        counts = d.get("counts")
        if not isinstance(bounds, (list, tuple)) or not bounds:
            raise ValueError("sketch dict missing bounds")
        sk = cls(bounds)
        if (
            not isinstance(counts, (list, tuple))
            or len(counts) != len(sk.bounds) + 1
            or any((isinstance(c, bool) or not isinstance(c, int) or c < 0)
                   for c in counts)
        ):
            raise ValueError("sketch dict counts malformed")
        sk._counts = [int(c) for c in counts]
        sk._sum = float(d.get("sum", 0.0))
        sk._count = int(d.get("count", sum(counts)))
        return sk


class SLOTracker:
    """Per-engine SLO state: one sketch per SLO + burn counters.

    ``observe`` is called from the engine scheduler thread on every
    emit; ``snapshot`` from HTTP handler threads (the /loadz body).
    """

    def __init__(self, thresholds: Optional[Mapping[str, float]] = None,
                 bounds: Sequence[float] = LATENCY_BUCKETS):
        self.thresholds: Dict[str, float] = dict(
            thresholds if thresholds is not None else DEFAULT_SLOS
        )
        self.sketches: Dict[str, Sketch] = {
            name: Sketch(bounds) for name in self.thresholds
        }
        self._lock = threading.Lock()
        self._burn: Dict[str, int] = {name: 0 for name in self.thresholds}

    def observe(self, slo: str, seconds: float) -> bool:
        """Record one observation; returns True when it breached the
        SLO threshold (the engine's journey layer captures the breaching
        request as a /debug/slowz exemplar on a True return)."""
        sk = self.sketches.get(slo)
        if sk is None:
            return False  # unknown SLO name must not crash the emit path
        sk.observe(seconds)
        if seconds > self.thresholds[slo]:
            with self._lock:
                self._burn[slo] += 1
            METRICS.inc("substratus_slo_burn_total", {"slo": slo})
            return True
        return False

    def burn(self, slo: str) -> int:
        with self._lock:
            return self._burn.get(slo, 0)

    def snapshot(self) -> dict:
        """{slo: {threshold_s, burn, sketch}} — the /loadz payload the
        fleet aggregator merges (gateway/fleet.py)."""
        with self._lock:
            burn = dict(self._burn)
        return {
            name: {
                "threshold_s": self.thresholds[name],
                "burn": burn[name],
                "sketch": self.sketches[name].to_dict(),
            }
            for name in self.thresholds
        }
