"""RBAC authorization for the /metrics endpoint.

The reference protects controller metrics with a kube-rbac-proxy sidecar
(config/install-kind/manager_patch.yaml: --upstream=127.0.0.1:8080,
SubjectAccessReview-based) scraped by a Prometheus ServiceMonitor with the
scraper's ServiceAccount bearer token (config/prometheus/monitor.yaml).

Here the proxy is in-process: the probe server authenticates the bearer
token with a TokenReview and authorizes the request with a
SubjectAccessReview against the `/metrics` non-resource URL — the same two
API calls kube-rbac-proxy makes — so no sidecar image is needed and the
flow is testable against the in-memory fake apiserver.
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from substratus_tpu.kube.client import KubeClient, KubeError

# Cache decisions briefly (kube-rbac-proxy does the same): Prometheus
# scrapes every few seconds with the same token, and each miss costs two
# apiserver round trips.
CACHE_TTL_S = 60.0


class MetricsAuthorizer:
    """allow(header) -> (http_status, reason); 200 means serve the page."""

    def __init__(self, kube: KubeClient, ttl_s: float = CACHE_TTL_S):
        self.kube = kube
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._cache: dict[str, Tuple[float, int, str]] = {}

    def allow(self, authorization: Optional[str]) -> Tuple[int, str]:
        if not authorization or not authorization.startswith("Bearer "):
            return 401, "missing bearer token"
        token = authorization[len("Bearer "):].strip()
        if not token:
            return 401, "empty bearer token"
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(token)
            if hit and now - hit[0] < self.ttl_s:
                return hit[1], hit[2]
        status, reason = self._check(token)
        if status < 500:  # never cache apiserver hiccups as verdicts
            with self._lock:
                self._cache[token] = (now, status, reason)
                if len(self._cache) > 1024:  # bound memory under token churn
                    self._cache.pop(next(iter(self._cache)))
        return status, reason

    def _check(self, token: str) -> Tuple[int, str]:
        try:
            tr = self.kube.create({
                "apiVersion": "authentication.k8s.io/v1",
                "kind": "TokenReview",
                "spec": {"token": token},
            })
        except KubeError as e:
            return 500, f"tokenreview failed: {e}"
        tstatus = tr.get("status", {})
        if not tstatus.get("authenticated"):
            return 401, "token not authenticated"
        user = tstatus.get("user", {})
        try:
            sar = self.kube.create({
                "apiVersion": "authorization.k8s.io/v1",
                "kind": "SubjectAccessReview",
                "spec": {
                    "user": user.get("username", ""),
                    "groups": user.get("groups", []),
                    "nonResourceAttributes": {"path": "/metrics", "verb": "get"},
                },
            })
        except KubeError as e:
            return 500, f"subjectaccessreview failed: {e}"
        if not sar.get("status", {}).get("allowed"):
            return 403, f"user {user.get('username', '?')} not allowed"
        return 200, "ok"
