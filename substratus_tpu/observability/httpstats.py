"""The one HTTP-response counter both serving tiers share.

`substratus_http_requests_total{endpoint,code}` is stamped on every
response the model server AND the gateway send — the denominator that
makes shed rate (429/503/504 over total) a one-query dashboard across
tiers (docs/observability.md "Gateway"). Lives here, not in either
tier, so the family is described exactly once and the endpoint
normalization can't drift between them.
"""
from __future__ import annotations

from substratus_tpu.observability.metrics import METRICS

METRICS.describe(
    "substratus_http_requests_total",
    "HTTP responses sent, by endpoint and status code.", type="counter",
)

# Endpoints worth per-path cardinality; everything else (scanner 404s,
# typos) folds into "other" so it can't mint unbounded series.
KNOWN_ENDPOINTS = frozenset((
    "/", "/metrics", "/loadz", "/healthz",
    "/v1/completions", "/v1/chat/completions", "/v1/models",
    "/debug/profile", "/debug/tracez", "/debug/requestz",
    "/debug/perfz", "/debug/eventz",
))


def endpoint_label(path: str) -> str:
    return path if path in KNOWN_ENDPOINTS else "other"


def count_http_response(path: str, status: int) -> None:
    METRICS.inc(
        "substratus_http_requests_total",
        {"endpoint": endpoint_label(path), "code": str(status)},
    )
