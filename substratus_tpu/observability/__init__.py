"""Telemetry for every plane: metrics registry, span tracer, health server.

Public API — import from here, not the submodules:

    from substratus_tpu.observability import METRICS, tracer, serve_health

  * ``METRICS`` / ``Metrics`` / ``Histogram`` — process-global Prometheus
    registry (counters, gauges, fixed-bucket histograms; text format 0.0.4
    with HELP/TYPE and label escaping);
  * ``tracer`` / ``Tracer`` / ``SpanContext`` — dependency-free span
    tracing with contextvar propagation and JSONL export;
  * ``serve_health`` — /healthz /readyz /metrics HTTP(S) server with
    optional TokenReview/SubjectAccessReview RBAC (``MetricsAuthorizer``);
  * ``lint_exposition`` — exposition-format validator (make metrics-lint);
  * ``parse_traceparent`` / ``format_traceparent`` / ``inject_headers`` /
    ``context_from_env`` — W3C trace-context propagation across HTTP,
    gRPC metadata, and spawned-job env vars (observability/propagation.py);
  * ``EVENTS`` / ``EventRecorder`` — Kubernetes-Event-shaped, count-deduped
    bounded event stream with optional kube write-through
    (observability/events.py).
"""
from substratus_tpu.observability.metrics import (  # noqa: F401
    LATENCY_BUCKETS,
    METRICS,
    RATIO_BUCKETS,
    THROUGHPUT_BUCKETS,
    Histogram,
    Metrics,
    escape_label_value,
    lint_exposition,
    quantile_from_buckets,
)
from substratus_tpu.observability.tracing import (  # noqa: F401
    Span,
    SpanContext,
    Tracer,
    current_trace_id,
    tracer,
)
from substratus_tpu.observability.propagation import (  # noqa: F401
    context_from_env,
    current_traceparent,
    deterministic_traceparent,
    format_traceparent,
    inject_headers,
    parse_traceparent,
)
from substratus_tpu.observability.events import (  # noqa: F401
    EVENTS,
    EventRecorder,
)
from substratus_tpu.observability.health import serve_health  # noqa: F401
from substratus_tpu.observability.journey import (  # noqa: F401
    EVENT_TYPES,
    JourneyLog,
    RequestJourney,
    SlowRing,
)
from substratus_tpu.observability.sketch import (  # noqa: F401
    Sketch,
    SLOTracker,
)
from substratus_tpu.observability.timeline import (  # noqa: F401
    BUBBLE_CAUSES,
    StepTimeline,
)

__all__ = [
    "BUBBLE_CAUSES",
    "EVENTS",
    "EVENT_TYPES",
    "EventRecorder",
    "JourneyLog",
    "LATENCY_BUCKETS",
    "METRICS",
    "RequestJourney",
    "SlowRing",
    "RATIO_BUCKETS",
    "THROUGHPUT_BUCKETS",
    "Histogram",
    "Metrics",
    "SLOTracker",
    "Sketch",
    "Span",
    "SpanContext",
    "StepTimeline",
    "Tracer",
    "context_from_env",
    "current_trace_id",
    "current_traceparent",
    "deterministic_traceparent",
    "escape_label_value",
    "format_traceparent",
    "inject_headers",
    "parse_traceparent",
    "quantile_from_buckets",
    "serve_health",
    "tracer",
]
