"""Telemetry for every plane: metrics registry, span tracer, health server.

Public API — import from here, not the submodules:

    from substratus_tpu.observability import METRICS, tracer, serve_health

  * ``METRICS`` / ``Metrics`` / ``Histogram`` — process-global Prometheus
    registry (counters, gauges, fixed-bucket histograms; text format 0.0.4
    with HELP/TYPE and label escaping);
  * ``tracer`` / ``Tracer`` / ``SpanContext`` — dependency-free span
    tracing with contextvar propagation and JSONL export;
  * ``serve_health`` — /healthz /readyz /metrics HTTP(S) server with
    optional TokenReview/SubjectAccessReview RBAC (``MetricsAuthorizer``);
  * ``lint_exposition`` — exposition-format validator (make metrics-lint).
"""
from substratus_tpu.observability.metrics import (  # noqa: F401
    LATENCY_BUCKETS,
    METRICS,
    RATIO_BUCKETS,
    THROUGHPUT_BUCKETS,
    Histogram,
    Metrics,
    escape_label_value,
    lint_exposition,
)
from substratus_tpu.observability.tracing import (  # noqa: F401
    Span,
    SpanContext,
    Tracer,
    tracer,
)
from substratus_tpu.observability.health import serve_health  # noqa: F401

__all__ = [
    "LATENCY_BUCKETS",
    "METRICS",
    "RATIO_BUCKETS",
    "THROUGHPUT_BUCKETS",
    "Histogram",
    "Metrics",
    "Span",
    "SpanContext",
    "Tracer",
    "escape_label_value",
    "lint_exposition",
    "serve_health",
    "tracer",
]
