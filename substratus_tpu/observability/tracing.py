"""Dependency-free request/step tracing.

Spans nest through a contextvar (async- and generator-safe on the event
loop); work that hops threads — the serve engine's scheduler thread picking
up an HTTP request, a reconcile retried on the Manager thread — carries the
parent explicitly: capture `tracer.current_context()` where the work is
submitted and pass it as `parent=` where it runs. Finished spans land in a
bounded ring buffer (oldest evicted first, a crashed exporter can never
OOM the server) and export as JSONL, one span per line:

    {"trace_id": "32-hex", "span_id": "16-hex", "parent_id": "16-hex"|null,
     "name": "serve.completion", "start_us": <epoch micros>,
     "duration_us": <int>, "attributes": {...}, "status": "ok"|"error:Type"}

This is the OTel data model minus the SDK: the JSONL converts to OTLP
losslessly if a collector ever enters the deployment.
"""
from __future__ import annotations

import contextvars
import json
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, NamedTuple, Optional


class SpanContext(NamedTuple):
    trace_id: str
    span_id: str


_current: "contextvars.ContextVar[Optional[SpanContext]]" = (
    contextvars.ContextVar("substratus_span", default=None)
)

# Distinguishes "parent not given" (inherit the contextvar) from an
# EXPLICIT parent — including an explicit None, which means "root span".
# Before this sentinel existed, a worker thread passing parent=None (e.g.
# a Request whose submitter had no active span) silently inherited
# whatever the contextvar held on that thread, mis-parenting the span
# under export-ordering edge cases.
_UNSET = object()


class Span:
    """A single timed operation; use as a context manager. Exceptions
    propagate — the span just records `error:<ExcType>` on the way out."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "attributes",
        "status", "_tracer", "_start_wall_us", "_start", "_token",
    )

    def __init__(
        self, tracer: "Tracer", name: str,
        parent, attributes: Dict[str, object],
    ):
        self._tracer = tracer
        self.name = name
        if parent is _UNSET:
            parent = _current.get()
        self.trace_id = (
            parent.trace_id if parent else uuid.uuid4().hex
        )
        self.span_id = uuid.uuid4().hex[:16]
        self.parent_id = parent.span_id if parent else None
        self.attributes = dict(attributes)
        self.status = "ok"
        self._start_wall_us = 0
        self._start = 0.0
        self._token = None

    def set_attribute(self, key: str, value: object) -> "Span":
        self.attributes[key] = value
        return self

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    def __enter__(self) -> "Span":
        self._start_wall_us = time.time_ns() // 1_000
        self._start = time.perf_counter()
        self._token = _current.set(self.context())
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        duration_us = int((time.perf_counter() - self._start) * 1e6)
        if self._token is not None:
            _current.reset(self._token)
        if exc_type is not None:
            self.status = f"error:{exc_type.__name__}"
        self._tracer._record(
            {
                "trace_id": self.trace_id,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "name": self.name,
                "start_us": self._start_wall_us,
                "duration_us": duration_us,
                "attributes": self.attributes,
                "status": self.status,
            }
        )
        return False  # never swallow


class _Attached:
    """Context manager that pins `_current` to a given context (tracer
    .attach). No span is recorded; exit restores the previous value."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[SpanContext]):
        self._ctx = ctx
        self._token = None

    def __enter__(self) -> Optional[SpanContext]:
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current.reset(self._token)
        return False


class Tracer:
    """Ring-buffered span collector. `capacity` bounds memory; JSONL export
    drains a snapshot without blocking recorders."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._spans: "deque[dict]" = deque(maxlen=capacity)
        self.dropped = 0  # spans evicted by the ring since the last clear

    def span(self, name: str, parent=_UNSET, **attributes) -> Span:
        """A new span. `parent` semantics: omitted -> inherit the calling
        context's active span (contextvar); an explicit SpanContext ->
        that parent, authoritatively; an explicit None -> a ROOT span.
        Explicit always wins — the contextvar is never consulted once the
        caller said what the parent is."""
        return Span(self, name, parent, attributes)

    def current_context(self) -> Optional[SpanContext]:
        """The active span's context — capture this before handing work to
        another thread, then pass it as `parent=` there."""
        return _current.get()

    def attach(self, ctx: Optional[SpanContext]):
        """Adopt a (remote) context as the calling context's current span
        without recording anything — subsequent spans parent under it.
        Returns a context manager; a None ctx attaches 'no span' (useful
        to isolate background work from an ambient trace)."""
        return _Attached(ctx)

    def _record(self, span: dict) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(span)

    def finished(self) -> List[dict]:
        """Snapshot of buffered finished spans, oldest first."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    def to_jsonl(self) -> str:
        return "".join(
            json.dumps(s, separators=(",", ":"), default=str) + "\n"
            for s in self.finished()
        )

    def export_jsonl(self, path: str) -> int:
        """Append buffered spans to `path`; returns the number written.
        The buffer is drained only on success, so a full disk retries the
        same spans next flush instead of dropping them silently."""
        spans = self.finished()
        if not spans:
            return 0
        data = "".join(
            json.dumps(s, separators=(",", ":"), default=str) + "\n"
            for s in spans
        )
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(data)
        with self._lock:
            for _ in range(min(len(spans), len(self._spans))):
                self._spans.popleft()
        return len(spans)


tracer = Tracer()


def current_trace_id() -> Optional[str]:
    """Trace id of the calling context's active span, or None. The log
    correlation hook: broad exception handlers that swallow deliberately
    include this in their log line so the swallow is findable from
    /debug/tracez (see the broad-except lint, docs/development.md)."""
    ctx = _current.get()
    return ctx.trace_id if ctx is not None else None
