"""Cloud abstraction (reference: internal/cloud/cloud.go:20-85).

Same interface surface: name, auto-configure, image/artifact addressing,
principal association, bucket mounting. Implementations: `gcp` (GKE + GCS
FUSE + workload identity + TPU slices) and `local` (hostPath bucket for kind
clusters and tests — the reference's `kind` cloud)."""
from __future__ import annotations

import os
from abc import ABC, abstractmethod
from typing import Any, Dict, Optional

from substratus_tpu.cloud.common import CommonConfig, artifact_url, image_url


class Cloud(ABC):
    def __init__(self, cfg: Optional[CommonConfig] = None):
        self.cfg = cfg or CommonConfig()

    @property
    @abstractmethod
    def name(self) -> str: ...

    def auto_configure(self) -> None:
        """Fill config from the environment/metadata where possible."""

    def object_built_image_url(self, obj) -> str:
        return image_url(self.cfg, obj.namespace, obj.KIND, obj.name)

    def object_artifact_url(self, obj) -> str:
        return artifact_url(self.cfg, obj.namespace, obj.KIND, obj.name)

    @abstractmethod
    def associate_principal(self, sa_namespace: str, sa_name: str) -> str:
        """Returns the cloud principal bound to a k8s ServiceAccount."""

    @abstractmethod
    def mount_bucket(
        self,
        pod_metadata: Dict[str, Any],
        pod_spec: Dict[str, Any],
        container: Dict[str, Any],
        name: str,
        bucket_url: str,
        mounts: Dict[str, str],  # subpath-in-bucket -> container path
        read_only: bool = True,
    ) -> None:
        """Attach bucket storage to a pod at /content/* paths."""


class GCPCloud(Cloud):
    """GKE: GCS-FUSE CSI mounts + workload identity annotations
    (reference gcp.go:28-140)."""

    @property
    def name(self) -> str:
        return "gcp"

    def __init__(self, cfg: Optional[CommonConfig] = None):
        super().__init__(cfg)
        self.project_id = os.environ.get("PROJECT_ID", "")
        self.cluster_location = os.environ.get("CLUSTER_LOCATION", "")

    _metadata_reachable: Optional[bool] = None

    def _metadata_get(self, path: str) -> Optional[str]:
        """One GCE metadata-server value, or None off-GCE / on error.
        GCE_METADATA_HOST is the standard override (also how tests stub
        the server). Reference: gcp.go:28-54 via cloud.google.com/go/
        compute/metadata.

        The first unreachable probe is cached (like metadata.OnGCE()) so
        off-GCE boot pays one connect attempt, not one per lookup; DNS for
        the conventional hostname only resolves on that first attempt."""
        import socket
        import urllib.error
        import urllib.request

        if self._metadata_reachable is False:
            return None
        host = os.environ.get("GCE_METADATA_HOST", "metadata.google.internal")
        if self._metadata_reachable is None:
            try:
                socket.create_connection(
                    (host.rsplit(":", 1)[0],
                     int(host.rsplit(":", 1)[1]) if ":" in host else 80),
                    timeout=2.0,
                ).close()
                self._metadata_reachable = True
            except OSError:
                self._metadata_reachable = False
                return None
        req = urllib.request.Request(
            f"http://{host}/computeMetadata/v1/{path}",
            headers={"Metadata-Flavor": "Google"},
        )
        try:
            with urllib.request.urlopen(req, timeout=2.0) as resp:
                if resp.headers.get("Metadata-Flavor") != "Google":
                    return None  # some other server squatting the name
                return resp.read().decode().strip()
        except (urllib.error.URLError, OSError, TimeoutError):
            return None

    def auto_configure(self) -> None:
        """Fill unset config from the GCE metadata server, then derive the
        conventional defaults — env always wins (reference gcp.go:28-71:
        ProjectID, cluster-name, cluster-location from metadata; registry/
        bucket/principal derived from project)."""
        self.project_id = os.environ.get("PROJECT_ID", self.project_id)
        if not self.project_id:
            self.project_id = self._metadata_get("project/project-id") or ""
        # CommonConfig falls back to "default" when CLUSTER_NAME is unset;
        # only an explicit env/config value beats the metadata server.
        if ("CLUSTER_NAME" not in os.environ
                and self.cfg.cluster_name in ("", "default")):
            self.cfg.cluster_name = (
                self._metadata_get("instance/attributes/cluster-name")
                or self.cfg.cluster_name
            )
        if not self.cluster_location:
            self.cluster_location = (
                self._metadata_get("instance/attributes/cluster-location")
                or ""
            )
        region = self.cluster_location
        if region.count("-") == 2:  # zone like us-central1-a -> region
            region = region.rsplit("-", 1)[0]
        if not self.cfg.registry_url and self.project_id and region:
            self.cfg.registry_url = (
                f"{region}-docker.pkg.dev/{self.project_id}/substratus"
            )
        if not self.cfg.artifact_bucket_url and self.project_id:
            self.cfg.artifact_bucket_url = (
                f"gs://{self.project_id}-substratus-artifacts"
            )
        if not self.cfg.principal and self.project_id:
            self.cfg.principal = (
                f"substratus@{self.project_id}.iam.gserviceaccount.com"
            )

    def associate_principal(self, sa_namespace: str, sa_name: str) -> str:
        return (
            f"{self.cfg.cluster_name}-{sa_namespace}-{sa_name}@"
            f"{self.project_id}.iam.gserviceaccount.com"
        )

    def workload_identity_annotation(self, principal: str) -> Dict[str, str]:
        return {"iam.gke.io/gcp-service-account": principal}

    def mount_bucket(self, pod_metadata, pod_spec, container, name,
                     bucket_url, mounts, read_only=True) -> None:
        bucket, _, prefix = bucket_url.removeprefix("gs://").partition("/")
        pod_metadata.setdefault("annotations", {}).update(
            {
                "gke-gcsfuse/volumes": "true",
                "gke-gcsfuse/cpu-limit": "2",
                "gke-gcsfuse/memory-limit": "2Gi",
                "gke-gcsfuse/ephemeral-storage-limit": "10Gi",
            }
        )
        pod_spec.setdefault("volumes", []).append(
            {
                "name": name,
                "csi": {
                    "driver": "gcsfuse.csi.storage.gke.io",
                    "readOnly": read_only,
                    "volumeAttributes": {
                        "bucketName": bucket,
                        "mountOptions": "implicit-dirs,uid=0,gid=0",
                    },
                },
            }
        )
        for sub, target in mounts.items():
            container.setdefault("volumeMounts", []).append(
                {
                    "name": name,
                    "mountPath": target,
                    "subPath": f"{prefix}/{sub}".lstrip("/"),
                    "readOnly": read_only,
                }
            )


class LocalCloud(Cloud):
    """hostPath `/bucket` as the artifact store with a `tar://`-style local
    scheme (reference kind.go:23-94); identity operations are no-ops. Used by
    kind clusters and the controller test suite."""

    @property
    def name(self) -> str:
        return "local"

    def __init__(self, cfg: Optional[CommonConfig] = None, root: str = "/bucket"):
        cfg = cfg or CommonConfig()
        if not cfg.artifact_bucket_url:
            cfg.artifact_bucket_url = f"local://{root}"
        if not cfg.registry_url:
            cfg.registry_url = "registry.local:5000"
        super().__init__(cfg)
        self.root = root

    def associate_principal(self, sa_namespace: str, sa_name: str) -> str:
        return f"local-{sa_namespace}-{sa_name}"

    def mount_bucket(self, pod_metadata, pod_spec, container, name,
                     bucket_url, mounts, read_only=True) -> None:
        path = bucket_url.removeprefix("local://")
        pod_spec.setdefault("volumes", []).append(
            {"name": name, "hostPath": {"path": path, "type": "DirectoryOrCreate"}}
        )
        for sub, target in mounts.items():
            container.setdefault("volumeMounts", []).append(
                {
                    "name": name,
                    "mountPath": target,
                    "subPath": sub,
                    "readOnly": read_only,
                }
            )


def new_cloud(name: Optional[str] = None) -> Cloud:
    """Factory (reference cloud.go:48-85): CLOUD env, else local."""
    name = name or os.environ.get("CLOUD", "").lower() or "local"
    if name == "gcp":
        c: Cloud = GCPCloud()
    elif name in ("local", "kind"):
        c = LocalCloud()
    else:
        raise ValueError(f"unknown cloud {name!r} (known: gcp, local)")
    c.auto_configure()
    return c
