"""Deterministic artifact/image addressing (reference: internal/cloud/
common.go:18-66; rationale docs/design.md:80-137).

Artifacts and images are addressed by *identity* (cluster/namespace/kind/
name), not content: re-applying the same CR into a fresh cluster with an
existing bucket finds its prior outputs. The bucket path hashes the identity
string so paths stay short and uniform.
"""
from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field


@dataclass
class CommonConfig:
    """Env-driven operator config (reference common.go:11-16, envFrom the
    `system` ConfigMap)."""

    cluster_name: str = field(
        default_factory=lambda: os.environ.get("CLUSTER_NAME", "default")
    )
    artifact_bucket_url: str = field(
        default_factory=lambda: os.environ.get("ARTIFACT_BUCKET_URL", "")
    )
    registry_url: str = field(
        default_factory=lambda: os.environ.get("REGISTRY_URL", "")
    )
    principal: str = field(
        default_factory=lambda: os.environ.get("PRINCIPAL", "")
    )

    def validate(self) -> None:
        missing = [
            k
            for k in ("artifact_bucket_url", "registry_url")
            if not getattr(self, k)
        ]
        if missing:
            raise ValueError(f"missing cloud config: {missing}")


def object_hash(cluster: str, namespace: str, kind: str, name: str) -> str:
    """md5 of the identity path (reference common.go:45-66)."""
    ident = f"clusters/{cluster}/namespaces/{namespace}/{kind.lower()}s/{name}"
    return hashlib.md5(ident.encode()).hexdigest()


def artifact_url(cfg: CommonConfig, namespace: str, kind: str, name: str) -> str:
    h = object_hash(cfg.cluster_name, namespace, kind, name)
    return f"{cfg.artifact_bucket_url.rstrip('/')}/{h}"


def image_url(cfg: CommonConfig, namespace: str, kind: str, name: str) -> str:
    """registry/cluster-kind-ns-name:latest (reference common.go:18-43)."""
    tag = f"{cfg.cluster_name}-{kind.lower()}-{namespace}-{name}"
    return f"{cfg.registry_url.rstrip('/')}/{tag}:latest"
