from substratus_tpu.cloud.base import Cloud, new_cloud
from substratus_tpu.cloud.common import artifact_url, image_url, object_hash

__all__ = ["Cloud", "new_cloud", "artifact_url", "image_url", "object_hash"]
