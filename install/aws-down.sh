#!/usr/bin/env bash
# EKS teardown (reference: install/scripts/aws-down.sh). Mirrors aws-up.sh:
# cluster, IRSA policy, ECR repo, artifact bucket.
set -euo pipefail

: "${AWS_ACCOUNT_ID:?set AWS_ACCOUNT_ID}"
REGION=${REGION:-us-west-2}
CLUSTER=${CLUSTER:-substratus}
BUCKET=${BUCKET:-${AWS_ACCOUNT_ID}-${CLUSTER}-artifacts}
REPO=${REPO:-${CLUSTER}}

eksctl delete cluster --name "${CLUSTER}" --region "${REGION}" || true

aws iam delete-policy \
  --policy-arn "arn:aws:iam::${AWS_ACCOUNT_ID}:policy/${CLUSTER}-artifacts" \
  2>/dev/null || true

aws ecr delete-repository --repository-name "${REPO}" \
  --region "${REGION}" --force >/dev/null 2>&1 || true

# The artifact bucket holds model/dataset artifacts: refuse to destroy it
# unless asked (the reference's `aws s3 rb` failed on non-empty buckets
# anyway — this makes the data-loss step explicit).
if [ "${DELETE_ARTIFACTS:-no}" = "yes" ]; then
  aws s3 rb "s3://${BUCKET}" --region "${REGION}" --force || true
else
  echo "kept s3://${BUCKET} (set DELETE_ARTIFACTS=yes to remove)"
fi
