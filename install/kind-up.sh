#!/usr/bin/env bash
# Local kind cluster bring-up (reference: install/kind/up.sh).
# Creates a kind cluster with the /bucket hostPath + NodePort 30080 mapping
# the local SCI storage handler needs, then installs CRDs + operator + SCI.
set -euo pipefail
cd "$(dirname "$0")/.."

CLUSTER=${CLUSTER:-substratus}

cat <<EOF | kind create cluster --name "$CLUSTER" --config=-
kind: Cluster
apiVersion: kind.x-k8s.io/v1alpha4
nodes:
  - role: control-plane
    extraMounts:
      - hostPath: /tmp/substratus-bucket
        containerPath: /bucket
    extraPortMappings:
      - containerPort: 30080
        hostPort: 30080
      - containerPort: 30500
        hostPort: 5000
containerdConfigPatches:
  # Trust the in-cluster registry (config/registry-kind/registry.yaml) over
  # plain HTTP; localhost:5000 resolves to its NodePort on every node.
  - |-
    [plugins."io.containerd.grpc.v1.cri".registry.mirrors."localhost:5000"]
      endpoint = ["http://localhost:30500"]
EOF

make install-manifests
kubectl apply -f install/substratus-tpu.yaml
kubectl apply -f config/registry-kind/registry.yaml
kubectl create configmap system -n substratus \
  --from-literal=CLOUD=local \
  --from-literal=CLUSTER_NAME="$CLUSTER" \
  --from-literal=ARTIFACT_BUCKET_URL=local:///bucket \
  --from-literal=REGISTRY_URL=localhost:5000 \
  --from-literal=PRINCIPAL=local \
  --dry-run=client -o yaml | kubectl apply -f -

echo "kind cluster '$CLUSTER' ready; try: sub apply -f examples/facebook-opt-125m/ --wait"
