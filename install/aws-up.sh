#!/usr/bin/env bash
# EKS bring-up (reference: install/scripts/aws-up.sh — EKS + S3 + ECR +
# karpenter GPU pools). TPUs are a GCP-only accelerator, so the AWS stack
# here is operator + data/CPU-serving parity: the controllers, the S3 SCI
# backend (IRSA-authenticated signed URLs), dataset loads and CPU model
# serving all run on EKS; Model training/serving CRs that ask for
# `resources.tpu` park with an explanatory condition until scheduled on a
# GKE cluster. The reference's karpenter+nvidia-device-plugin GPU pools
# have no TPU analogue on AWS.
set -euo pipefail
cd "$(dirname "$0")/.."

: "${AWS_ACCOUNT_ID:?set AWS_ACCOUNT_ID}"
REGION=${REGION:-us-west-2}
CLUSTER=${CLUSTER:-substratus}
BUCKET=${BUCKET:-${AWS_ACCOUNT_ID}-${CLUSTER}-artifacts}
REPO=${REPO:-${CLUSTER}}

# Artifact bucket + image repository (md5-addressed artifacts land here;
# see cloud/ and sci/ S3 backends).
aws s3 mb "s3://${BUCKET}" --region "${REGION}" 2>/dev/null || true
aws ecr create-repository --repository-name "${REPO}" \
  --region "${REGION}" >/dev/null 2>&1 || true

# Cluster: managed CPU node group; OIDC enabled for IRSA (the S3 SCI
# server exchanges its ServiceAccount for the role below — sci/ S3
# backend's get-modify-set trust-policy flow).
eksctl create cluster \
  --name "${CLUSTER}" --region "${REGION}" \
  --with-oidc \
  --node-type m6i.xlarge \
  --nodes 1 --nodes-min 1 --nodes-max 4 \
  || eksctl upgrade cluster --name "${CLUSTER}" --region "${REGION}"

# IRSA role for the SCI server + workload SAs (bucket-scoped).
cat > /tmp/substratus-s3-policy.json <<EOF
{
  "Version": "2012-10-17",
  "Statement": [{
    "Effect": "Allow",
    "Action": ["s3:GetObject", "s3:PutObject", "s3:ListBucket"],
    "Resource": [
      "arn:aws:s3:::${BUCKET}",
      "arn:aws:s3:::${BUCKET}/*"
    ]
  }]
}
EOF
aws iam create-policy \
  --policy-name "${CLUSTER}-artifacts" \
  --policy-document file:///tmp/substratus-s3-policy.json \
  >/dev/null 2>&1 || true
eksctl create iamserviceaccount \
  --cluster "${CLUSTER}" --region "${REGION}" \
  --namespace substratus --name sci \
  --attach-policy-arn "arn:aws:iam::${AWS_ACCOUNT_ID}:policy/${CLUSTER}-artifacts" \
  --approve || true

# JobSet controller (the gang primitive; harmless on CPU-only clusters,
# required if this kubeconfig is ever pointed at TPU pools).
kubectl apply --server-side -f \
  https://github.com/kubernetes-sigs/jobset/releases/latest/download/manifests.yaml

make install-manifests
kubectl apply -f install/substratus-tpu.yaml
kubectl create configmap system -n substratus \
  --from-literal=CLOUD=aws \
  --from-literal=CLUSTER_NAME="${CLUSTER}" \
  --from-literal=REGION="${REGION}" \
  --from-literal=ARTIFACT_BUCKET_URL="s3://${BUCKET}" \
  --from-literal=REGISTRY_URL="${AWS_ACCOUNT_ID}.dkr.ecr.${REGION}.amazonaws.com/${REPO}" \
  --from-literal=PRINCIPAL="arn:aws:iam::${AWS_ACCOUNT_ID}:role/${CLUSTER}-artifacts" \
  --from-literal=SCI_BACKEND=s3 \
  --dry-run=client -o yaml | kubectl apply -f -

echo "EKS cluster '${CLUSTER}' ready (operator + S3/IRSA; TPU asks park" \
     "until pointed at a GKE TPU cluster — see docs/troubleshooting.md)"
