#!/usr/bin/env bash
# GKE bring-up with TPU node pools (reference: install/gcp/up.sh:17-111,
# which provisioned NAP + L4 GPU pools; this provisions v5e TPU pools).
set -euo pipefail
cd "$(dirname "$0")/.."

PROJECT=${PROJECT:-$(gcloud config get-value project)}
ZONE=${ZONE:-us-central2-b}
CLUSTER=${CLUSTER:-substratus}
BUCKET=${BUCKET:-${PROJECT}-substratus-artifacts}

gcloud container clusters create "$CLUSTER" \
  --project "$PROJECT" --zone "$ZONE" \
  --release-channel rapid \
  --workload-pool="${PROJECT}.svc.id.goog" \
  --addons GcsFuseCsiDriver \
  --machine-type e2-standard-4 --num-nodes 1

# Single-host v5e pool (1-8 chips per node, autoscaled to zero when idle).
gcloud container node-pools create tpu-v5e-single \
  --project "$PROJECT" --zone "$ZONE" --cluster "$CLUSTER" \
  --machine-type ct5lp-hightpu-4t \
  --enable-autoscaling --min-nodes 0 --max-nodes 8 --num-nodes 0 \
  --spot

# Multi-host v5e-16 slice pool (4 hosts x 4 chips; JobSet gangs land here).
gcloud container node-pools create tpu-v5e-16 \
  --project "$PROJECT" --zone "$ZONE" --cluster "$CLUSTER" \
  --machine-type ct5lp-hightpu-4t \
  --tpu-topology 4x4 \
  --enable-autoscaling --min-nodes 0 --max-nodes 4 --num-nodes 0 \
  --spot

# JobSet controller (multi-host slice gangs).
kubectl apply --server-side -f \
  https://github.com/kubernetes-sigs/jobset/releases/latest/download/manifests.yaml

gsutil mb -p "$PROJECT" "gs://${BUCKET}" 2>/dev/null || true

make install-manifests
kubectl apply -f install/substratus-tpu.yaml
kubectl create configmap system -n substratus \
  --from-literal=CLOUD=gcp \
  --from-literal=PROJECT_ID="$PROJECT" \
  --from-literal=CLUSTER_NAME="$CLUSTER" \
  --from-literal=ARTIFACT_BUCKET_URL="gs://${BUCKET}" \
  --from-literal=REGISTRY_URL="gcr.io/${PROJECT}/substratus" \
  --from-literal=PRINCIPAL="substratus@${PROJECT}.iam.gserviceaccount.com" \
  --from-literal=SCI_BACKEND=gcs \
  --dry-run=client -o yaml | kubectl apply -f -

echo "GKE cluster '$CLUSTER' ready with v5e pools; try the examples/ CRs"
