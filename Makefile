# Dev targets (reference: Makefile:80-290 — manifests/generate/protogen/
# test tiers/installation-manifests).

PY ?= python

.PHONY: test test-int lint lint-fast metrics-lint trace-lint manifests api-docs protogen nbwatch spm bench bench-train bench-smoke bench-compare gateway-smoke fleet-smoke journey-smoke autoscale-smoke rollout-smoke gateway-bench adapter-bench disagg-bench overlap-bench spec-bench prefix-bench batchgen-bench graft image install-manifests

test:
	$(PY) -m pytest tests/ -x -q

# Whole-repo static analysis (hack/sublint.py + substratus_tpu/analysis/):
# shard (PartitionSpec axes vs the parallel/mesh.py registry), hostsync
# (host-device syncs reachable from the engine decode loop / trainer
# step), concurrency (cross-thread writes, thread lifecycle, blocking in
# async), broad-except, lockorder (interprocedural lock cycles /
# blocking-while-locked), lifecycle (alloc-free, pin-unpin,
# shutdown-before-close), protodrift (wire-format producer/consumer key
# agreement + endianness) — plus the wrapped metrics/trace runtime
# lints. Exits nonzero on any unsuppressed finding; suppressions require
# reasons (docs/development.md#static-analysis-sublint). Diffs against
# the committed sublint.sarif baseline (stable fingerprints: only NEW
# findings fail; the suppression count ratchets against it) and then
# regenerates it as the CI artifact.
lint:
	$(PY) hack/sublint.py --baseline sublint.sarif --sarif sublint.sarif

# AST families only — no runtime deps, no subprocesses; fast enough for
# a pre-commit hook and runs on a box with nothing but python installed.
lint-fast:
	$(PY) hack/sublint.py --checks \
	  shard,hostsync,concurrency,broad-except,lockorder,lifecycle,protodrift

# Aliases into the unified driver: one check family each. `make
# trace-lint FILES=path.jsonl` still lints a real span export directly.
metrics-lint:
	$(PY) hack/sublint.py --checks metrics

trace-lint:
ifdef FILES
	$(PY) hack/trace_lint.py $(FILES)
else
	$(PY) hack/sublint.py --checks trace
endif

# Controller integration tier only (fake apiserver; reference
# `make test-integration`).
test-int:
	$(PY) -m pytest tests/test_controllers.py tests/test_sci.py -q

manifests:
	$(PY) -m substratus_tpu.api.crdgen > config/crd/substratus-crds.yaml

api-docs:
	$(PY) -m substratus_tpu.api.docgen > docs/api.md

protogen:
	protoc --python_out=substratus_tpu/sci --proto_path=substratus_tpu/sci \
	  substratus_tpu/sci/sci.proto

nbwatch:
	g++ -O2 -Wall -o native/nbwatch native/nbwatch.cc

# C++ SentencePiece encoder for the serving hot path (ctypes-loaded;
# pure-Python fallback when absent).
spm:
	g++ -O2 -Wall -shared -fPIC -o native/libspm_tokenizer.so native/spm_tokenizer.cc

bench:
	$(PY) bench.py

# The second BASELINE primary metric: 7B LoRA finetune step-time.
bench-train:
	$(PY) tools/bench_train.py

# CPU-scaled captures of BOTH baseline primary metrics plus the
# 2-process lockstep gang bench, each piped through the schema validator
# — proves every capture path emits one valid JSON line without a chip.
bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py --config tiny --batch 4 --cache-len 128 \
	  --steps 8 --quantize int8 --no-fallback --probe-timeout 60 \
	  --probe-budget 120 | $(PY) hack/bench_compare.py --validate -
	JAX_PLATFORMS=cpu $(PY) tools/bench_train.py --smoke \
	  | $(PY) hack/bench_compare.py --validate -
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --gang 2 \
	  --transport tcp --long-admission 8200 \
	  | $(PY) hack/bench_compare.py --validate -

# Gateway chaos smoke: 2 in-process CPU replicas behind the routing
# gateway, scripted kill mid-stream / hedge / recover-after-backoff
# (tools/gateway_smoke.py; the pytest chaos test drives the same
# harness). JSON verdict on stdout, nonzero exit on any stage failing.
gateway-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/gateway_smoke.py

# Fleet telemetry smoke (ISSUE 11 acceptance): 2 in-process replicas
# behind the gateway — /debug/fleetz must show BOTH replicas with
# non-empty ring-buffer series + EWMA signals, a consistent fleet
# rollup, merged SLO percentiles from the /loadz poll path, and the
# substratus_fleet_* families on /metrics (tools/fleet_smoke.py).
fleet-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/fleet_smoke.py

# Request-journey smoke (ISSUE 17 acceptance): gateway + 1 prefill + 1
# decode worker in-process, ONE chat request through the gateway — the
# response's x-trace-id must resolve on /debug/journeyz to a single
# stitched journey whose waterfall shows all four hops (gateway edge,
# prefill, KV handoff, decode) and `sub trace <id>` must render it
# (tools/journey_smoke.py). JSON verdict on stdout.
journey-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/journey_smoke.py

# Closed-loop autoscaling smoke (ISSUE 12 acceptance): one in-process
# replica behind the gateway, the real decision core closing the loop
# — a load ramp scales the fleet up, sustained idleness drains one
# replica back out, and EVERY stream issued across both transitions
# must end [DONE] with no error event (tools/autoscale_smoke.py; the
# pytest chaos suite drives the same FleetSupervisor and adds the
# kill-one-replica self-healing leg).
autoscale-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/autoscale_smoke.py

# Zero-downtime rollout smoke (ISSUE 20 acceptance): two in-process
# replicas behind the gateway, the real RolloutCoordinator rolling the
# fleet to "seed:1" and back to "seed:0" over /swapz + /loadz while
# SSE streams pump continuously — both replicas must converge on each
# rollout's weights_version and EVERY stream issued across both
# rollouts must end [DONE] with no error event
# (tools/rollout_smoke.py, controller/rollout.py).
rollout-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/rollout_smoke.py

# Routed-2-replica vs direct throughput/TTFT capture (ISSUE 5
# acceptance: routed aggregate tok/s >= 1.7x single replica on the
# smoke shape). Spawns replica server subprocesses; heavier than
# gateway-smoke, so not part of the CI tests workflow.
gateway-bench:
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --gateway 2 \
	  --max-tokens 32 | $(PY) hack/bench_compare.py --validate -

# Multi-tenant adapter packing capture (ISSUE 6 acceptance): a mixed
# 4-adapter engine vs a base-only engine on the same shape with the
# simulated device step — packed aggregate tok/s must stay within 15%
# of base (tests/test_adapters.py asserts the ratio; this target
# validates the capture schema).
adapter-bench:
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --adapters 4 \
	  | $(PY) hack/bench_compare.py --validate -

# Disaggregated prefill/decode capture (ISSUE 7 acceptance): a
# 1-prefill + 1-decode pair over the real TCP KV handoff vs 2
# monolithic engines on the same shape under a prompt-burst workload
# with the simulated device step — burst-window p99 inter-token
# latency must drop >=30% with aggregate tok/s within 10%
# (docs/serving.md "Disaggregated prefill/decode").
disagg-bench:
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --disagg \
	  | $(PY) hack/bench_compare.py --validate -

# Overlapped decode scheduler capture (ISSUE 10 acceptance): one-step-
# ahead dispatch with on-device token feedback vs the synchronous
# scheduler on the same shape, simulated device step + real per-token
# detokenize host work in the emit path — steady-state inter-token
# mean must hold <= 1.15x the device floor with aggregate tok/s within
# 5% or better, greedy outputs token-exact (tests/test_overlap.py
# asserts; docs/performance.md "Overlapped scheduling"). The capture
# also embeds hard gates bench_compare --validate evaluates (ISSUE 11):
# bubble ratio <= 0.15, bubble attribution coverage >= 0.9, tok/s vs
# sync >= 0.95 — a host-path regression fails here WITH a cause
# (docs/performance.md "Pipeline-bubble attribution").
overlap-bench:
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --overlap \
	  | $(PY) hack/bench_compare.py --validate -

# Speculation x overlap composition capture (ISSUE 14 acceptance):
# plain / spec-only / overlap-only / spec+overlap engines on the same
# repetitive-prompt shape, simulated device step + the overlap leg's
# per-token host work — the composed engine's aggregate tok/s must
# beat BOTH single-lever legs (the pipelined spec rounds amortize the
# floor across accepted drafts while the one-step-ahead dispatch hides
# the proposal scan + emit work), greedy outputs token-exact across
# all four engines, and pipeline_flushes_total{reason="spec"} must not
# move (docs/performance.md "Speculative decoding";
# tests/test_spec_overlap.py asserts the same invariants in-process).
spec-bench:
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --spec-overlap \
	  | $(PY) hack/bench_compare.py --validate -

# Shared-prefix KV reuse capture (ROADMAP item 1 evidence): repeated
# system-prompt workload, prefix registry on vs off — TTFT and
# aggregate tok/s.
prefix-bench:
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --prefix-reuse \
	  | $(PY) hack/bench_compare.py --validate -

# Batch-generation actor gang capture (ISSUE 9 acceptance): a 2-actor
# gang draining one shared prompt manifest through the continuous-
# refill driver vs one identical actor, simulated device step — gang
# aggregate tok/s must reach >=1.8x single AND steady-state decode
# slot occupancy >=0.9 (tests/test_batchgen.py asserts both; this
# target validates the capture schema — docs/batch-generation.md).
batchgen-bench:
	JAX_PLATFORMS=cpu $(PY) tools/engine_bench.py --smoke --batchgen 2 \
	  | $(PY) hack/bench_compare.py --validate -

# Bench JSON schema + >10% regression gate (hack/bench_compare.py):
# self-tests that a synthetic 20% regression fails and that the repo's
# historical BENCH_* trajectory still loads.
bench-compare:
	$(PY) hack/bench_compare.py --self-test

graft:
	$(PY) __graft_entry__.py

image:
	docker build -t ghcr.io/substratus-tpu/runtime:latest .

# Single-file install manifest (reference `make installation-manifests`).
# Explicit --- separators: bare concatenation merges adjacent YAML docs.
install-manifests: manifests
	{ cat config/crd/substratus-crds.yaml; echo '---'; \
	  cat config/manager/manager.yaml; echo '---'; \
	  cat config/sci/deployment.yaml; } > install/substratus-tpu.yaml
