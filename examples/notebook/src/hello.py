print("hello from substratus-tpu")
